"""Program rewrite toolkit: Pass registry + DAG pattern matcher.

Reference: paddle/fluid/framework/ir/pass.h:38 (Pass / PassRegistry),
ir/graph_pattern_detector.cc (PDNode / PDPattern / GraphPatternDetector),
ir/fuse_pass_base.h.  The reference rewrites an SSA graph of C++ nodes;
here the Program's op list IS the graph (vars link ops by name), so a
pass is a Python function over Blocks and a pattern is a list of op
templates with producer constraints — the same detector contract with
two orders of magnitude less machinery.

TPU-first note: XLA already fuses elementwise chains, so passes here are
about *semantic* rewrites XLA cannot do — mapping subgraphs onto Pallas
kernels (fused attention), deleting train-only ops for inference, dead
code elimination to cut trace/compile time.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .core import Block, Operator, Program
from .dtype import VarType

# --------------------------------------------------------------------------
# pass registry (reference: pass.h REGISTER_PASS)
# --------------------------------------------------------------------------
PASS_REGISTRY: Dict[str, type] = {}


class Pass:
    """Base pass: override apply_impl(program) -> program."""

    name: str = ""

    def apply(self, program: Program) -> Program:
        """Apply the pass; under ``FLAGS_verify_passes`` every
        application is bracketed by the static verifier
        (framework/verifier.py): snapshot the dataflow before, check
        for motion hazards / broken invariants after, and raise a
        VerifyError naming this pass, the op index and the hazard.
        Every current and future pass inherits the gate — the
        structural replacement for per-pass bit-identity arguments."""
        from . import verifier

        snap = verifier.snapshot(program) if verifier.enabled() else None
        out = self.apply_impl(program)
        out = out if out is not None else program
        if snap is not None:
            verifier.verify_pass(snap, out,
                                 self.name or type(self).__name__)
        return out

    def apply_impl(self, program: Program) -> Optional[Program]:
        raise NotImplementedError

    def set(self, **attrs):
        """Attribute injection like the reference's Pass::Set."""
        for k, v in attrs.items():
            setattr(self, k, v)
        return self


def register_pass(name: str):
    def deco(cls):
        cls.name = name
        PASS_REGISTRY[name] = cls
        return cls

    return deco


def get_pass(name: str, **attrs) -> Pass:
    try:
        cls = PASS_REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"pass {name!r} is not registered; have {sorted(PASS_REGISTRY)}"
        ) from None
    return cls().set(**attrs)


class PassManager:
    """Ordered pass pipeline (reference: the analysis pass manager /
    build-strategy pass application loop)."""

    def __init__(self, passes: Sequence):
        self.passes = [p if isinstance(p, Pass) else get_pass(p)
                       for p in passes]

    def apply(self, program: Program) -> Program:
        for p in self.passes:
            program = p.apply(program)
        return program


# --------------------------------------------------------------------------
# graph utilities
# --------------------------------------------------------------------------
def producer_map(block: Block) -> Dict[str, Operator]:
    """var name -> last op writing it (SSA-enough for matched subgraphs)."""
    prod = {}
    for op_ in block.ops:
        for names in op_.outputs.values():
            for n in names:
                prod[n] = op_
    return prod


def consumer_count(block: Block) -> Dict[str, int]:
    cnt: Dict[str, int] = {}
    for op_ in block.ops:
        for names in op_.inputs.values():
            for n in names:
                cnt[n] = cnt.get(n, 0) + 1
    return cnt


def remove_ops(block: Block, ops: Sequence[Operator]):
    dead = set(id(o) for o in ops)
    block.ops[:] = [o for o in block.ops if id(o) not in dead]
    block.program._bump_version()


# --------------------------------------------------------------------------
# pattern matcher (reference: graph_pattern_detector.cc)
# --------------------------------------------------------------------------
class OpTemplate:
    """One PDNode: an op of `type` whose given input slots are fed by the
    named output of an earlier template ("producer.Slot")."""

    def __init__(self, name: str, type: str,
                 inputs: Optional[Dict[str, str]] = None,
                 predicate: Optional[Callable[[Operator], bool]] = None):
        self.name = name
        self.type = type
        self.inputs = inputs or {}
        self.predicate = predicate


def match_pattern(block: Block, templates: Sequence[OpTemplate],
                  allow_shared_intermediates: bool = False
                  ) -> List[Dict[str, Operator]]:
    """Find non-overlapping matches of the template DAG.

    Like GraphPatternDetector: templates are matched in order; each
    non-root template's constrained input slots must be fed by the var a
    previously-matched template produced.  Unless
    allow_shared_intermediates, every intermediate var (produced and
    consumed inside the match) must have no consumers outside the match —
    the detector's IsIntermediate() safety rule, which keeps a rewrite
    from deleting a value someone else reads.
    """
    prod = producer_map(block)
    cons = consumer_count(block)
    by_type: Dict[str, List[Operator]] = {}
    for op_ in block.ops:
        by_type.setdefault(op_.type, []).append(op_)

    matches: List[Dict[str, Operator]] = []
    used: set = set()

    def backtrack(i: int, bound: Dict[str, Operator]):
        if i == len(templates):
            matches.append(dict(bound))
            return True  # first match per root wins (greedy)
        t = templates[i]
        for cand in by_type.get(t.type, []):
            if id(cand) in used or any(id(cand) == id(o) for o in bound.values()):
                continue
            if t.predicate is not None and not t.predicate(cand):
                continue
            ok = True
            for slot, src in t.inputs.items():
                src_name, src_slot = src.split(".")
                src_op = bound.get(src_name)
                if src_op is None:
                    ok = False
                    break
                in_names = cand.inputs.get(slot, [])
                out_names = src_op.outputs.get(src_slot, [])
                if not in_names or not out_names or in_names[0] not in out_names:
                    ok = False
                    break
                if prod.get(in_names[0]) is not src_op:
                    ok = False  # someone overwrote the var in between
                    break
            if not ok:
                continue
            bound[t.name] = cand
            if backtrack(i + 1, bound):
                return True
            del bound[t.name]
        return False

    # try every candidate root, greedily claiming matched ops
    for root in list(by_type.get(templates[0].type, [])):
        if id(root) in used:
            continue
        if templates[0].predicate is not None and not templates[0].predicate(root):
            continue
        bound = {templates[0].name: root}
        if backtrack(1, bound):
            m = matches[-1]
            # intermediate-safety check
            if not allow_shared_intermediates and not _intermediates_private(
                    m, cons):
                matches.pop()
                continue
            used.update(id(o) for o in m.values())

    return matches


def _intermediates_private(match: Dict[str, Operator],
                           cons: Dict[str, int]) -> bool:
    ops = list(match.values())
    internal_inputs: Dict[str, int] = {}
    produced: Dict[str, Operator] = {}
    for o in ops:
        for names in o.outputs.values():
            for n in names:
                produced[n] = o
    for o in ops:
        for names in o.inputs.values():
            for n in names:
                if n in produced:
                    internal_inputs[n] = internal_inputs.get(n, 0) + 1
    for n, k in internal_inputs.items():
        if cons.get(n, 0) != k:
            return False  # consumed outside the match too
    return True


# --------------------------------------------------------------------------
# built-in passes
# --------------------------------------------------------------------------
@register_pass("remove_training_ops_pass")
class RemoveTrainingOpsPass(Pass):
    """Drop backward/optimize/lr-sched ops by op role (reference: the
    op-role filter inside Program._prune_with_input, io.py:1093) —
    always run before inference DCE, else in-place optimizer updates
    alias param names and reverse DCE drags training back in."""

    def apply_impl(self, program):
        from ..backward import OP_ROLE_KEY, OpRole

        mask = OpRole.Backward | OpRole.Optimize | OpRole.LRSched
        block = program.global_block()
        block.ops[:] = [
            op_ for op_ in block.ops
            if not (int(op_.attrs.get(OP_ROLE_KEY, 0)) & mask)
        ]
        program._bump_version()
        return program


@register_pass("dead_code_elimination_pass")
class DeadCodeEliminationPass(Pass):
    """Remove ops whose outputs are transitively unused (reference:
    ir/graph_helper + the inference prune pass).  `targets` (names) are
    kept alive; host/side-effect ops are always kept.  strict=True also
    removes persistable-writing ops not needed by the targets (the
    inference-prune behavior); the default keeps them (state updates are
    external effects in a training program)."""

    targets: Sequence[str] = ()
    strict: bool = False

    SIDE_EFFECT_OPS = frozenset({
        "print", "assert_op", "send", "recv", "send_barrier",
        "fetch_barrier", "checkpoint_notify", "listen_and_serv",
        "c_sync_calc_stream", "c_sync_comm_stream", "barrier",
    })

    def apply_impl(self, program):
        block = program.global_block()
        live = set(self.targets)
        keep: List[Operator] = []
        EMPTY = "@EMPTY@"
        for op_ in reversed(block.ops):
            out_names = [n for ns in op_.outputs.values() for n in ns
                         if n != EMPTY]
            is_live = any(n in live for n in out_names)
            if not is_live and not self.strict:
                # training graphs keep host side-effects; the strict
                # (inference) mode prunes them like the reference's
                # fetch-rooted prune does
                is_live = op_.type in self.SIDE_EFFECT_OPS
            # state-carrying ops (optimizers etc.) write their inputs in
            # place: output name == input name means external effect when
            # that var is persistable
            if not is_live and not self.strict:
                for n in out_names:
                    v = block._find_var_recursive(n)
                    if v is not None and getattr(v, "persistable", False):
                        is_live = True
                        break
            if is_live:
                keep.append(op_)
                for ns in op_.inputs.values():
                    live.update(n for n in ns if n != EMPTY)
        block.ops[:] = list(reversed(keep))
        program._bump_version()
        return program


@register_pass("delete_dropout_pass")
class DeleteDropoutPass(Pass):
    """Inference cleanup (reference: ir/delete_dropout_op_pass.cc):
    upscale_in_train dropout becomes identity (assign); downgrade_in_infer
    becomes scale(1-p)."""

    def apply_impl(self, program):
        block = program.global_block()
        for i, op_ in enumerate(list(block.ops)):
            if op_.type != "dropout":
                continue
            impl = op_.attrs.get("dropout_implementation", "downgrade_in_infer")
            p = op_.attrs.get("dropout_prob", 0.5)
            x = op_.inputs["X"]
            out = {"Out": op_.outputs["Out"]}
            idx = block.ops.index(op_)
            remove_ops(block, [op_])
            if impl == "upscale_in_train":
                block._insert_op(idx, "assign", inputs={"X": x}, outputs=out)
            else:
                block._insert_op(idx, "scale", inputs={"X": x}, outputs=out,
                                 attrs={"scale": 1.0 - p, "bias": 0.0})
        return program


def _is_scale_like(op_):
    return op_.type == "scale" and op_.attrs.get("bias", 0.0) in (0, 0.0)


def _is_qk_matmul(op_):
    """Q @ K^T with plain Q and no trailing alpha surprises beyond the
    scalar the rewrite folds into `scale`."""
    return (op_.attrs.get("transpose_Y", False)
            and not op_.attrs.get("transpose_X", False))


def _is_av_matmul(op_):
    """softmax(probs) @ V, untransposed, unscaled — the fused kernel has
    no epilogue scaling."""
    return (not op_.attrs.get("transpose_Y", False)
            and not op_.attrs.get("transpose_X", False)
            and op_.attrs.get("alpha", 1.0) in (1, 1.0))


def _is_last_axis_softmax(op_):
    return op_.attrs.get("axis", -1) in (-1, 3)


def _is_default_axis_add(op_):
    """The fused attention kernel applies BiasQK under plain numpy
    broadcasting; an elementwise_add with an explicit non-default axis
    broadcast would be silently reinterpreted, so only fuse the default
    (trailing-aligned) form."""
    return op_.attrs.get("axis", -1) == -1


@register_pass("fuse_multihead_attention_pass")
class FuseMultiheadAttentionPass(Pass):
    """Map the naive attention subgraph onto the Pallas flash-attention
    kernel (reference intent: ir/multihead_matmul_fuse_pass.cc — there it
    targets the cuda fused kernel; here `fused_multihead_attention`
    lowers to ops/pallas_kernels.py flash_attention).

    Matches, for Q/K/V of layout (batch, heads, seq, head_dim):
        qk = matmul(Q, K, transpose_Y=True)        [alpha = any]
        s  = scale(qk)                             [optional]
        m  = elementwise_add(s, mask)              [optional]
        sm = softmax(m)
        out = matmul(sm, V)
    and replaces the chain with one fused_multihead_attention op.
    """

    def apply_impl(self, program):
        block = program.global_block()
        # longest variant first so optional nodes are claimed when present
        variants = [
            [OpTemplate("qk", "matmul", predicate=_is_qk_matmul),
             OpTemplate("scale", "scale", {"X": "qk.Out"},
                        predicate=_is_scale_like),
             OpTemplate("mask", "elementwise_add", {"X": "scale.Out"},
                        predicate=_is_default_axis_add),
             OpTemplate("softmax", "softmax", {"X": "mask.Out"},
                        predicate=_is_last_axis_softmax),
             OpTemplate("av", "matmul", {"X": "softmax.Out"},
                        predicate=_is_av_matmul)],
            [OpTemplate("qk", "matmul", predicate=_is_qk_matmul),
             OpTemplate("scale", "scale", {"X": "qk.Out"},
                        predicate=_is_scale_like),
             OpTemplate("softmax", "softmax", {"X": "scale.Out"},
                        predicate=_is_last_axis_softmax),
             OpTemplate("av", "matmul", {"X": "softmax.Out"},
                        predicate=_is_av_matmul)],
            [OpTemplate("qk", "matmul", predicate=_is_qk_matmul),
             OpTemplate("mask", "elementwise_add", {"X": "qk.Out"},
                        predicate=_is_default_axis_add),
             OpTemplate("softmax", "softmax", {"X": "mask.Out"},
                        predicate=_is_last_axis_softmax),
             OpTemplate("av", "matmul", {"X": "softmax.Out"},
                        predicate=_is_av_matmul)],
            [OpTemplate("qk", "matmul", predicate=_is_qk_matmul),
             OpTemplate("softmax", "softmax", {"X": "qk.Out"},
                        predicate=_is_last_axis_softmax),
             OpTemplate("av", "matmul", {"X": "softmax.Out"},
                        predicate=_is_av_matmul)],
        ]
        fused = 0
        for templates in variants:
            for m in match_pattern(block, templates):
                self._rewrite(block, m)
                fused += 1
        self.fused_count = fused
        return program

    def _rewrite(self, block, m):
        qk, av = m["qk"], m["av"]
        q_name = qk.inputs["X"][0]
        k_name = qk.inputs["Y"][0]
        v_name = av.inputs["Y"][0]
        out = {"Out": av.outputs["Out"]}
        scale = qk.attrs.get("alpha", 1.0)
        if "scale" in m:
            scale = scale * m["scale"].attrs.get("scale", 1.0)
        inputs = {"Q": [q_name], "K": [k_name], "V": [v_name]}
        if "mask" in m:
            inputs["BiasQK"] = [m["mask"].inputs["Y"][0]]
        # insert where the AV matmul was: every value the fused op
        # reads (Q/K/V and the mask) is produced before av, which is not
        # guaranteed for qk (the mask may be computed after it)
        idx = block.ops.index(av)
        idx -= sum(1 for o in m.values() if block.ops.index(o) < idx)
        remove_ops(block, list(m.values()))
        block._insert_op(idx, "fused_multihead_attention",
                         inputs=inputs, outputs=out,
                         attrs={"scale": float(scale), "causal": False})


# --------------------------------------------------------------------------
# fused BN(+add)+activation passes (reference: ir/fuse_bn_act_pass.cc,
# ir/fuse_bn_add_act_pass.cc — the cudnn fused-BN rewrite; here the
# targets are ops/fused_ops.py fused_batch_norm_act /
# fused_bn_add_activation, whose closed-form backward avoids the
# vjp-replay residuals).  Unlike the attention pass these rewrite the
# forward AND its backward chain together, because by the time the
# executor sees a training program append_backward has already emitted
# relu_grad/elementwise_add_grad/batch_norm_grad ops that reference the
# unfused intermediates.
# --------------------------------------------------------------------------
def _consumers(block):
    cons: Dict[str, List[Operator]] = {}
    for op_ in block.ops:
        for names in op_.inputs.values():
            for n in names:
                cons.setdefault(n, []).append(op_)
    return cons


class _FuseBNActBase(Pass):
    #: vars the rewrite must not make unavailable (fetch targets)
    protected: Sequence[str] = ()

    def apply_impl(self, program):
        fused = 0
        for block in program.blocks:
            # vars referenced from ANY other block (while/cond carries,
            # sub-block free vars) are invisible to this block's consumer
            # map — never fuse away their producers
            external = set()
            for other in program.blocks:
                if other is block:
                    continue
                for op_ in other.ops:
                    for names in op_.inputs.values():
                        external.update(names)
                    for names in op_.outputs.values():
                        external.update(names)
            fused += self._apply_block(block, external)
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


@register_pass("fuse_bn_act_pass")
class FuseBNActPass(_FuseBNActBase):
    """batch_norm -> relu  (and its grad chain)  ==> fused_batch_norm_act."""

    def _apply_block(self, block, external=()):
        protected = set(self.protected) | set(external)
        fused = 0
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            for bn in list(block.ops):
                if bn.type != "batch_norm":
                    continue
                y0 = bn.outputs.get("Y", [None])[0]
                if not y0 or y0 in protected:
                    continue
                users = cons.get(y0, [])
                relu = next((o for o in users if o.type == "relu"
                             and o.inputs.get("X", [None])[0] == y0), None)
                if relu is None:
                    continue
                bn_grad = next((o for o in users if o.type == "batch_norm_grad"
                                and o.inputs.get("Y", [None])[0] == y0), None)
                relu_grad = next(
                    (o for o in users if o.type == "relu_grad"
                     and o.inputs.get("X", [None])[0] == y0), None)
                allowed = {id(relu), id(bn_grad), id(relu_grad)}
                if any(id(o) not in allowed for o in users):
                    continue
                y1 = relu.outputs["Out"][0]
                if (bn_grad is None) != (relu_grad is None):
                    continue  # half a backward: leave it alone
                if bn_grad is not None:
                    # relu_grad must feed exactly bn_grad's dY, and the
                    # rewrite stops producing dy0 — so it must not be a
                    # fetch target either
                    dy0 = relu_grad.outputs.get("X@GRAD", [None])[0]
                    if (dy0 in protected
                            or bn_grad.inputs.get("Y@GRAD", [None])[0] != dy0
                            or any(id(o) != id(bn_grad)
                                   for o in cons.get(dy0, []))):
                        continue
                    if relu_grad.inputs.get("Out", [None])[0] != y1:
                        continue
                # ---- rewrite forward
                idx = block.ops.index(bn)
                attrs = dict(bn.attrs)
                attrs["act_type"] = "relu"
                inputs = {k: list(v) for k, v in bn.inputs.items()}
                outputs = {k: list(v) for k, v in bn.outputs.items()}
                outputs["Y"] = [y1]
                remove_ops(block, [bn, relu])
                block._insert_op(idx, "fused_batch_norm_act",
                                 inputs=inputs, outputs=outputs, attrs=attrs)
                # ---- rewrite backward
                if bn_grad is not None:
                    gidx = block.ops.index(relu_grad)
                    ginputs = {
                        "X": list(bn.inputs["X"]),
                        "Y": [y1],
                        "Scale": list(bn.inputs["Scale"]),
                        "SavedMean": list(bn.outputs["SavedMean"]),
                        "SavedVariance": list(bn.outputs["SavedVariance"]),
                        "Y@GRAD": list(relu_grad.inputs["Out@GRAD"]),
                    }
                    goutputs = {
                        "X@GRAD": list(bn_grad.outputs.get("X@GRAD", [])),
                        "Scale@GRAD": list(bn_grad.outputs.get("Scale@GRAD", [])),
                        "Bias@GRAD": list(bn_grad.outputs.get("Bias@GRAD", [])),
                    }
                    remove_ops(block, [relu_grad, bn_grad])
                    block._insert_op(gidx, "fused_batch_norm_act_grad",
                                     inputs=ginputs, outputs=goutputs,
                                     attrs=dict(attrs))
                fused += 1
                changed = True
                break
        return fused


@register_pass("fuse_bn_add_act_pass")
class FuseBNAddActPass(_FuseBNActBase):
    """batch_norm -> elementwise_add -> relu (and grads) ==>
    fused_bn_add_activation.  Only same-shape adds with the default axis
    are fused (a broadcasting add is not the cudnn pattern and the fused
    kernel would reinterpret it)."""

    def _apply_block(self, block, external=()):
        protected = set(self.protected) | set(external)
        fused = 0
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            for bn in list(block.ops):
                if bn.type != "batch_norm":
                    continue
                y0 = bn.outputs.get("Y", [None])[0]
                if not y0 or y0 in protected:
                    continue
                users = cons.get(y0, [])
                add = next((o for o in users if o.type == "elementwise_add"
                            and o.attrs.get("axis", -1) == -1
                            and y0 in (o.inputs.get("X", [None])[0],
                                       o.inputs.get("Y", [None])[0])), None)
                if add is None:
                    continue
                bn_grad = next((o for o in users if o.type == "batch_norm_grad"
                                and o.inputs.get("Y", [None])[0] == y0), None)
                # the replayed elementwise_add_grad desc re-reads the
                # forward's X/Y, so it legitimately appears among y0's
                # (and ya's) consumers
                add_grad = next(
                    (o for o in users if o.type == "elementwise_add_grad"
                     and o.inputs.get("X", [None]) == add.inputs.get("X")
                     and o.inputs.get("Y", [None]) == add.inputs.get("Y")),
                    None)
                if any(id(o) not in {id(add), id(bn_grad), id(add_grad)}
                       for o in users):
                    continue
                # z = the other operand; shapes must match exactly
                xn, yn = add.inputs["X"][0], add.inputs["Y"][0]
                z = xn if yn == y0 else yn
                bn_slot_is_y = yn == y0
                vy, vz = block._find_var_recursive(y0), \
                    block._find_var_recursive(z)
                if (vy is None or vz is None or vy.shape is None
                        or list(vy.shape) != list(vz.shape)):
                    continue
                ya = add.outputs["Out"][0]
                if ya in protected:
                    continue
                ya_users = cons.get(ya, [])
                relu = next((o for o in ya_users if o.type == "relu"
                             and o.inputs.get("X", [None])[0] == ya), None)
                if relu is None:
                    continue
                relu_grad = next(
                    (o for o in ya_users if o.type == "relu_grad"
                     and o.inputs.get("X", [None])[0] == ya), None)
                if any(id(o) not in {id(relu), id(relu_grad), id(add_grad)}
                       for o in ya_users):
                    continue
                if bn_grad is not None or relu_grad is not None \
                        or add_grad is not None:
                    if bn_grad is None or relu_grad is None \
                            or add_grad is None:
                        continue  # half a backward: leave it alone
                    dya = relu_grad.outputs.get("X@GRAD", [None])[0]
                    if (dya in protected
                            or add_grad.inputs.get("Out@GRAD", [None])[0] != dya
                            or any(id(o) != id(add_grad)
                                   for o in cons.get(dya, []))):
                        continue
                    # add_grad's bn-side output must feed exactly bn_grad
                    bn_side = "Y@GRAD" if bn_slot_is_y else "X@GRAD"
                    z_side = "X@GRAD" if bn_slot_is_y else "Y@GRAD"
                    dy0 = add_grad.outputs.get(bn_side, [None])[0]
                    if (dy0 is None or dy0 in protected
                            or bn_grad.inputs.get("Y@GRAD", [None])[0] != dy0
                            or any(id(o) != id(bn_grad)
                                   for o in cons.get(dy0, []))):
                        continue
                    dz = add_grad.outputs.get(z_side, [None])[0]
                    if relu_grad.inputs.get("Out", [None])[0] != \
                            relu.outputs["Out"][0]:
                        continue
                y1 = relu.outputs["Out"][0]
                # ---- rewrite forward
                idx = block.ops.index(relu)
                idx -= sum(1 for o in (bn, add)
                           if block.ops.index(o) < idx)
                attrs = dict(bn.attrs)
                attrs["act_type"] = "relu"
                inputs = {k: list(v) for k, v in bn.inputs.items()}
                inputs["Z"] = [z]
                outputs = {k: list(v) for k, v in bn.outputs.items()}
                outputs["Y"] = [y1]
                remove_ops(block, [bn, add, relu])
                block._insert_op(idx, "fused_bn_add_activation",
                                 inputs=inputs, outputs=outputs, attrs=attrs)
                # ---- rewrite backward
                if bn_grad is not None:
                    gidx = block.ops.index(relu_grad)
                    ginputs = {
                        "X": list(bn.inputs["X"]),
                        "Y": [y1],
                        "Scale": list(bn.inputs["Scale"]),
                        "SavedMean": list(bn.outputs["SavedMean"]),
                        "SavedVariance": list(bn.outputs["SavedVariance"]),
                        "Y@GRAD": list(relu_grad.inputs["Out@GRAD"]),
                    }
                    goutputs = {
                        "X@GRAD": list(bn_grad.outputs.get("X@GRAD", [])),
                        "Scale@GRAD": list(bn_grad.outputs.get("Scale@GRAD", [])),
                        "Bias@GRAD": list(bn_grad.outputs.get("Bias@GRAD", [])),
                        "Z@GRAD": [dz] if dz else [],
                    }
                    remove_ops(block, [relu_grad, add_grad, bn_grad])
                    block._insert_op(gidx, "fused_bn_add_activation_grad",
                                     inputs=ginputs, outputs=goutputs,
                                     attrs=dict(attrs))
                fused += 1
                changed = True
                break
        return fused


# --------------------------------------------------------------------------
# profile-ranked epilogue fusion (r14) — the Pallas fusion layer's IR
# half.  utils/cost_model.find_fusion_chains supplies the structural
# matches (so ranking and rewrite can never disagree), and
# rank_fusion_candidates orders them by modeled+measured memory-traffic
# savings; this pass rewrites them best-first onto the fused ops in
# ops/fused_ops.py (fused_conv_bn_act / fused_matmul_bias_act), forward
# and the matching grad chain together — the same fwd+bwd-paired shape
# as fuse_bn_act_pass, per the README "writing a safe IR pass"
# checklist.  Gated by FLAGS_tpu_fuse in the executor pipeline, applied
# AFTER the NHWC layout pass (the fused ops carry one layout attr and
# both pass orders are verifier-clean).
# --------------------------------------------------------------------------
@register_pass("fuse_epilogue_pass")
class FuseEpiloguePass(Pass):
    """conv2d -> batch_norm/fused_batch_norm_act/fused_bn_add_activation
    (+ grads) ==> fused_conv_bn_act;  mul/matmul -> elementwise_add(1-D
    bias) -> act (+ grads) ==> fused_matmul_bias_act."""

    #: vars the rewrite must not make unavailable (fetch targets)
    protected: Sequence[str] = ()

    #: attrs the fused_conv_bn_act lowering reads, by source op
    _CONV_ATTRS = ("strides", "paddings", "dilations", "groups",
                   "padding_algorithm", "data_format")
    _BN_ATTRS = ("momentum", "epsilon", "is_test", "use_global_stats")

    def apply_impl(self, program):
        from ..utils import cost_model as cmod

        block = program.global_block()
        protected = set(self.protected)
        for other in program.blocks:
            if other is block:
                continue
            for op_ in other.ops:
                for names in op_.inputs.values():
                    protected.update(names)
                for names in op_.outputs.values():
                    protected.update(names)
        # calibrate the cost model ONCE per application (the profile is
        # fixed for the whole rewrite; only the chain set changes as
        # rewrites land, so the per-iteration re-rank reuses this cm)
        profile = cmod.measured_profile()
        cm = cmod.CostModel()
        if profile:
            _, modeled = cmod.backward_timeline(block.ops, block, cm)
            cm = cm.calibrated(profile["step_s"], modeled)
        fused = 0
        self.report: List[dict] = []
        changed = True
        while changed:
            changed = False
            # re-rank after every rewrite: a fusion changes the consumer
            # structure the next match must see
            for cand in cmod.rank_fusion_candidates(program,
                                                    profile=profile, cm=cm):
                if cand["saved_bytes"] <= 0:
                    continue
                if self._rewrite(block, cand["chain"], protected):
                    fused += 1
                    self.report.append(
                        {k: cand[k] for k in
                         ("kind", "ops", "out", "saved_bytes", "est_saved_s",
                          "measured_epilogue_s", "score_s", "calibrated")})
                    changed = True
                    break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _merged_role_attrs(*grad_ops):
        out = {}
        roles = [o.attrs.get("op_role") for o in grad_ops
                 if o is not None and "op_role" in o.attrs]
        if roles:
            out["op_role"] = roles[0]
        rv: List[str] = []
        for o in grad_ops:
            if o is not None:
                rv.extend(o.attrs.get("op_role_var", []) or [])
        if rv:
            out["op_role_var"] = rv
        return out

    def _rewrite(self, block, ch, protected):
        if ch["kind"] == "conv_bn_act":
            return self._rewrite_conv(block, ch, protected)
        return self._rewrite_matmul(block, ch, protected)

    def _rewrite_conv(self, block, ch, protected):
        conv, bn = ch["conv"], ch["bn"]
        conv_grad, bn_grad = ch["conv_grad"], ch["bn_grad"]
        act_op, act_grad = ch["act_op"], ch["act_grad"]
        # vars the rewrite stops producing must not be fetch targets
        gone = set()
        if bn_grad is not None:
            gone.add(ch["dconv"])
        if ch.get("bn_y"):
            gone.add(ch["bn_y"])
            if act_grad is not None:
                gone.add(ch["bn_y"] + "@GRAD")
        if gone & protected:
            return False
        attrs = {k: conv.attrs[k] for k in self._CONV_ATTRS
                 if k in conv.attrs}
        attrs.update({k: bn.attrs[k] for k in self._BN_ATTRS
                      if k in bn.attrs})
        attrs["act_type"] = ch["act"]
        if conv.type == "depthwise_conv2d":
            attrs["depthwise"] = True
        if "op_role" in bn.attrs:
            attrs["op_role"] = bn.attrs["op_role"]
        inputs = {
            "Input": list(conv.inputs["Input"]),
            "Filter": list(conv.inputs["Filter"]),
            "Scale": list(bn.inputs["Scale"]),
            "Bias": list(bn.inputs["Bias"]),
            "Mean": list(bn.inputs["Mean"]),
            "Variance": list(bn.inputs["Variance"]),
        }
        if ch["z"]:
            inputs["Z"] = [ch["z"]]
        outputs = {
            "Output": [ch["out"]],
            "ConvOut": [ch["conv_out"]],
            "MeanOut": list(bn.outputs.get("MeanOut", [])),
            "VarianceOut": list(bn.outputs.get("VarianceOut", [])),
            "SavedMean": list(bn.outputs.get("SavedMean", [])),
            "SavedVariance": list(bn.outputs.get("SavedVariance", [])),
        }
        dead_fwd = [conv, bn] + ([act_op] if act_op is not None else [])
        last = act_op if act_op is not None else bn
        idx = block.ops.index(last)
        idx -= sum(1 for o in dead_fwd[:-1] if block.ops.index(o) < idx)
        remove_ops(block, dead_fwd)
        block._insert_op(idx, "fused_conv_bn_act",
                         inputs=inputs, outputs=outputs, attrs=attrs)
        if bn_grad is not None:
            gattrs = {k: v for k, v in attrs.items() if k != "op_role"}
            gattrs.update(self._merged_role_attrs(act_grad, bn_grad,
                                                  conv_grad))
            dy_in = (act_grad.inputs["Out@GRAD"] if act_grad is not None
                     else bn_grad.inputs["Y@GRAD"])
            ginputs = {
                "Input": list(conv.inputs["Input"]),
                "Filter": list(conv.inputs["Filter"]),
                "ConvOut": [ch["conv_out"]],
                "Output": [ch["out"]],
                "Scale": list(bn.inputs["Scale"]),
                "SavedMean": list(bn.outputs["SavedMean"]),
                "SavedVariance": list(bn.outputs["SavedVariance"]),
                "Output@GRAD": list(dy_in),
            }
            goutputs = {
                "Input@GRAD": list(conv_grad.outputs.get("Input@GRAD", [])),
                "Filter@GRAD": list(conv_grad.outputs.get("Filter@GRAD", [])),
                "Scale@GRAD": list(bn_grad.outputs.get("Scale@GRAD", [])),
                "Bias@GRAD": list(bn_grad.outputs.get("Bias@GRAD", [])),
            }
            if ch["z"] and bn_grad.outputs.get("Z@GRAD"):
                goutputs["Z@GRAD"] = list(bn_grad.outputs["Z@GRAD"])
            dead_bwd = ([act_grad] if act_grad is not None else []) + \
                [bn_grad, conv_grad]
            gidx = block.ops.index(dead_bwd[0])
            remove_ops(block, dead_bwd)
            block._insert_op(gidx, "fused_conv_bn_act_grad",
                             inputs=ginputs, outputs=goutputs, attrs=gattrs)
        return True

    def _rewrite_matmul(self, block, ch, protected):
        mm, add, act_op = ch["mm"], ch["add"], ch["act_op"]
        mm_grad, add_grad, act_grad = \
            ch["mm_grad"], ch["add_grad"], ch["act_grad"]
        gone = {ch["mm_out"], ch["add_out"]}
        if act_grad is not None:
            gone |= {ch["add_out"] + "@GRAD", ch["mm_out"] + "@GRAD"}
        if gone & protected:
            return False
        attrs = {
            "act_type": ch["act"],
            "x_num_col_dims": ch["xnc"],
            "axis": add.attrs.get("axis", -1),
        }
        if "op_role" in act_op.attrs:
            attrs["op_role"] = act_op.attrs["op_role"]
        inputs = {"X": list(mm.inputs["X"]), "Y": list(mm.inputs["Y"]),
                  "Bias": list(add.inputs["Y"])}
        idx = block.ops.index(act_op)
        idx -= sum(1 for o in (mm, add) if block.ops.index(o) < idx)
        remove_ops(block, [mm, add, act_op])
        block._insert_op(idx, "fused_matmul_bias_act", inputs=inputs,
                         outputs={"Out": [ch["out"]]}, attrs=attrs)
        if act_grad is not None:
            gattrs = {k: v for k, v in attrs.items() if k != "op_role"}
            gattrs.update(self._merged_role_attrs(act_grad, add_grad,
                                                  mm_grad))
            ginputs = {
                "X": list(mm.inputs["X"]), "Y": list(mm.inputs["Y"]),
                "Bias": list(add.inputs["Y"]),
                "Out@GRAD": list(act_grad.inputs["Out@GRAD"]),
            }
            goutputs = {
                "X@GRAD": list(mm_grad.outputs.get("X@GRAD", [])),
                "Y@GRAD": list(mm_grad.outputs.get("Y@GRAD", [])),
                "Bias@GRAD": list(add_grad.outputs.get("Y@GRAD", [])),
            }
            gidx = block.ops.index(act_grad)
            remove_ops(block, [act_grad, add_grad, mm_grad])
            block._insert_op(gidx, "fused_matmul_bias_act_grad",
                             inputs=ginputs, outputs=goutputs, attrs=gattrs)
        return True


# --------------------------------------------------------------------------
# conv+BN inference fold (reference: ir/conv_bn_fuse_pass.cc) — needs the
# scope: the fold rewrites the conv FILTER VALUES (W' = W * scale*inv_std
# per output channel) and replaces the batch_norm with a per-channel bias
# add.  Inference-only: the bn must be running in is_test /
# use_global_stats mode.
# --------------------------------------------------------------------------
@register_pass("conv_bn_fuse_pass")
class ConvBNFusePass(Pass):
    scope = None
    protected: Sequence[str] = ()

    def apply_impl(self, program):
        import numpy as np

        fused = 0
        scope = self.scope
        if scope is None:
            self.fused_count = 0
            return program
        protected = set(self.protected)
        block = program.global_block()
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            prod = producer_map(block)
            for bn in list(block.ops):
                if bn.type != "batch_norm":
                    continue
                if not (bn.attrs.get("is_test")
                        or bn.attrs.get("use_global_stats")):
                    continue
                if bn.attrs.get("data_layout", "NCHW") not in ("NCHW",
                                                               "AnyLayout"):
                    continue  # the folded bias add below is axis=1 (NCHW)
                x0 = bn.inputs.get("X", [None])[0]
                conv = prod.get(x0)
                if conv is not None and conv.attrs.get(
                        "data_format", "NCHW") != "NCHW":
                    continue
                if (conv is None or conv.type != "conv2d"
                        or x0 in protected
                        or any(id(o) != id(bn) for o in cons.get(x0, []))):
                    continue
                w_name = conv.inputs["Filter"][0]
                vals = {}
                ok = True
                for slot in ("Scale", "Bias", "Mean", "Variance"):
                    v = scope.get(bn.inputs[slot][0])
                    if v is None:
                        ok = False
                        break
                    vals[slot] = np.asarray(v, np.float64)
                w = scope.get(w_name)
                if not ok or w is None:
                    continue
                # the filter must not be shared with another conv: scaling
                # it would silently change the other consumer
                if sum(1 for o in block.ops
                       if w_name in o.inputs.get("Filter", [])) > 1:
                    continue
                eps = bn.attrs.get("epsilon", 1e-5)
                a = vals["Scale"] / np.sqrt(vals["Variance"] + eps)
                b = vals["Bias"] - vals["Mean"] * a
                w_np = np.asarray(w)
                scope.set(w_name, (np.asarray(w_np, np.float64)
                                   * a[:, None, None, None]
                                   ).astype(w_np.dtype))
                y_name = bn.outputs["Y"][0]
                bias_name = y_name + "__bn_folded_bias"
                block.create_var(name=bias_name, shape=[int(a.shape[0])],
                                 dtype=VarType.FP32, persistable=True)
                scope.set(bias_name, b.astype(np.float32))
                idx = block.ops.index(bn)
                remove_ops(block, [bn])
                block._insert_op(idx, "elementwise_add",
                                 inputs={"X": [x0], "Y": [bias_name]},
                                 outputs={"Out": [y_name]},
                                 attrs={"axis": 1})
                fused += 1
                changed = True
                break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


# --------------------------------------------------------------------------
# embedding + eltwise-add + layer_norm fuse (reference:
# ir/embedding_eltwise_layernorm_fuse_pass.cc -> the
# fused_embedding_eltwise_layernorm op).  Matches k>=2 lookup_tables
# whose outputs sum through private default-axis adds into a last-axis
# layer_norm; inference-path only (the rewrite does not touch grads).
# --------------------------------------------------------------------------
@register_pass("embedding_eltwise_layernorm_fuse_pass")
class EmbeddingEltwiseLayernormFusePass(Pass):
    protected: Sequence[str] = ()

    def apply_impl(self, program):
        fused = 0
        block = program.global_block()
        protected = set(self.protected)
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            prod = producer_map(block)
            for ln in list(block.ops):
                if ln.type != "layer_norm":
                    continue
                if ln.attrs.get("begin_norm_axis", 1) != 2:
                    continue  # the fused op normalizes (b, s, h) over h
                # Mean/Variance side outputs must be dead
                if any(cons.get(n, []) for slot in ("Mean", "Variance")
                       for n in ln.outputs.get(slot, [])):
                    continue
                x0 = ln.inputs["X"][0]
                if x0 in protected:
                    continue
                lookups, adds = [], []
                ok = [True]

                def collect(name):
                    p = prod.get(name)
                    if p is None:
                        ok[0] = False
                        return
                    private = (len(cons.get(name, [])) == 1
                               and name not in protected)
                    if p.type == "elementwise_add" and \
                            p.attrs.get("axis", -1) == -1 and private:
                        adds.append(p)
                        collect(p.inputs["X"][0])
                        collect(p.inputs["Y"][0])
                    elif p.type in ("lookup_table", "lookup_table_v2") \
                            and private \
                            and p.attrs.get("padding_idx", -1) in (-1,):
                        lookups.append(p)
                    else:
                        ok[0] = False

                collect(x0)
                if not ok[0] or len(lookups) < 2 or not adds:
                    continue
                # the fused op applies the LN affine unconditionally, so
                # only layer_norms that HAVE Scale and Bias are fused
                if not ln.inputs.get("Scale") or not ln.inputs.get("Bias"):
                    continue
                ids = [lk.inputs["Ids"][0] for lk in lookups]
                embs = [lk.inputs["W"][0] for lk in lookups]
                inputs = {"Ids": ids, "Embs": embs,
                          "Scale": list(ln.inputs["Scale"]),
                          "Bias": list(ln.inputs["Bias"])}
                dead = adds + lookups + [ln]
                idx = block.ops.index(ln)
                idx -= sum(1 for o in dead if block.ops.index(o) < idx)
                remove_ops(block, dead)
                block._insert_op(
                    idx, "fused_embedding_eltwise_layernorm",
                    inputs=inputs, outputs={"Out": list(ln.outputs["Y"])},
                    attrs={"epsilon": ln.attrs.get("epsilon", 1e-5)})
                fused += 1
                changed = True
                break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


@register_pass("fc_fuse_pass")
class FcFusePass(Pass):
    """mul + elementwise_add [+ relu]  ==>  fc  (reference:
    ir/fc_fuse_pass.cc).  Inference-shape rewrite: only fires on forward
    chains with no grad consumers (run it from the inference
    PassStrategy, after remove_training_ops)."""

    protected: Sequence[str] = ()

    def apply_impl(self, program):
        fused = 0
        block = program.global_block()
        protected = set(self.protected)
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            for mul in list(block.ops):
                if mul.type != "mul" or \
                        mul.attrs.get("y_num_col_dims", 1) != 1:
                    continue
                y0 = mul.outputs["Out"][0]
                if y0 in protected:
                    continue
                # the fc kernel multiplies W as-is: only 2-D weights
                # match (mul itself flattens higher-rank Y; fc must not)
                wv = block._find_var_recursive(mul.inputs["Y"][0])
                if wv is None or wv.shape is None or len(wv.shape) != 2:
                    continue
                users = cons.get(y0, [])
                if len(users) != 1 or users[0].type != "elementwise_add":
                    continue
                add = users[0]
                # bias must be the non-mul operand, added along axis 1 of
                # a 2-D result (the fc bias shape), or the default axis
                xn, yn = add.inputs["X"][0], add.inputs["Y"][0]
                if xn != y0:
                    continue  # fc bias rides the Y slot in the fc pattern
                if add.attrs.get("axis", -1) not in (-1, 1):
                    continue
                # the Y operand must actually be a bias: a 1-D (or 1xN)
                # var, not a batch-shaped activation (the fc op reshapes
                # Bias to (1, n) — fusing an activation add would be a
                # silent wrong-result rewrite)
                bv = block._find_var_recursive(yn)
                if bv is None or bv.shape is None:
                    continue
                bshape = [d for d in bv.shape]
                if not (len(bshape) == 1
                        or (len(bshape) == 2 and bshape[0] == 1)):
                    continue
                bias = yn
                a1 = add.outputs["Out"][0]
                out_name = a1
                act = ""
                dead = [mul, add]
                a_users = cons.get(a1, [])
                if a1 not in protected and len(a_users) == 1 \
                        and a_users[0].type == "relu":
                    act = "relu"
                    out_name = a_users[0].outputs["Out"][0]
                    dead.append(a_users[0])
                idx = block.ops.index(mul)
                inputs = {"Input": list(mul.inputs["X"]),
                          "W": list(mul.inputs["Y"]),
                          "Bias": [bias]}
                attrs = {"in_num_col_dims":
                         mul.attrs.get("x_num_col_dims", 1),
                         "activation_type": act}
                remove_ops(block, dead)
                block._insert_op(idx, "fc", inputs=inputs,
                                 outputs={"Out": [out_name]}, attrs=attrs)
                fused += 1
                changed = True
                break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


@register_pass("seqpool_concat_fuse_pass")
class SeqpoolConcatFusePass(Pass):
    """N x sequence_pool feeding ONE concat(axis=1)  ==>
    fusion_seqpool_concat (reference: ir/seqpool_concat_fuse_pass.cc).
    All pools must share the pooltype; per-slot Length inputs ride
    along in order."""

    protected: Sequence[str] = ()

    def apply_impl(self, program):
        fused = 0
        block = program.global_block()
        protected = set(self.protected)
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            prod = producer_map(block)
            for cat in list(block.ops):
                if cat.type != "concat" or cat.attrs.get("axis", 0) != 1:
                    continue
                srcs = cat.inputs.get("X", [])
                pools = [prod.get(n) for n in srcs]
                if len(pools) < 2 or any(
                        p is None or p.type != "sequence_pool"
                        for p in pools):
                    continue
                ptypes = {(p.attrs.get("pooltype") or "SUM").upper()
                          for p in pools}
                if len(ptypes) != 1 or \
                        next(iter(ptypes)) not in ("SUM", "AVERAGE", "SQRT"):
                    continue
                # the fused kernel zero-fills empty sequences; a nonzero
                # pad_value pool must stay unfused to keep its semantics
                if any((p.attrs.get("pad_value") or 0.0) != 0.0
                       for p in pools):
                    continue
                # every pooled intermediate is private to this concat,
                # MaxIndex side outputs dead, names not protected
                ok = True
                for n, p in zip(srcs, pools):
                    if n in protected or len(cons.get(n, [])) != 1:
                        ok = False
                        break
                    for mi in p.outputs.get("MaxIndex", []):
                        if cons.get(mi, []):
                            ok = False
                            break
                if not ok:
                    continue
                xs, lens = [], []
                for p in pools:
                    xs.append(p.inputs["X"][0])
                    lens.extend(p.inputs.get("Length", []))
                if lens and len(lens) != len(pools):
                    continue  # mixed explicit/implicit lengths: leave it
                idx = block.ops.index(cat)
                idx -= sum(1 for p in pools if block.ops.index(p) < idx)
                inputs = {"X": xs}
                if lens:
                    inputs["Length"] = lens
                remove_ops(block, pools + [cat])
                block._insert_op(
                    idx, "fusion_seqpool_concat", inputs=inputs,
                    outputs={"Out": list(cat.outputs["Out"])},
                    attrs={"pooltype": next(iter(ptypes))})
                fused += 1
                changed = True
                break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


@register_pass("transpose_flatten_concat_fuse_pass")
class TransposeFlattenConcatFusePass(Pass):
    """N x (transpose2 -> flatten2) branches feeding ONE concat ==>
    fusion_transpose_flatten_concat (reference:
    ir/transpose_flatten_concat_fuse_pass.cc — the SSD/detection
    multi-head collection pattern).  All branches must share the
    transpose perm and flatten axis."""

    protected: Sequence[str] = ()

    def apply_impl(self, program):
        fused = 0
        block = program.global_block()
        protected = set(self.protected)
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            prod = producer_map(block)
            for cat in list(block.ops):
                if cat.type != "concat":
                    continue
                srcs = cat.inputs.get("X", [])
                flats = [prod.get(n) for n in srcs]
                if len(flats) < 2 or any(
                        f is None or f.type not in ("flatten2", "flatten")
                        for f in flats):
                    continue
                transposes = [prod.get(f.inputs["X"][0]) for f in flats]
                if any(t is None or t.type not in ("transpose2", "transpose")
                       for t in transposes):
                    continue
                perms = {tuple(t.attrs.get("axis", ())) for t in transposes}
                faxes = {int(f.attrs.get("axis", 1)) for f in flats}
                if len(perms) != 1 or len(faxes) != 1:
                    continue
                ok = True
                for f, t in zip(flats, transposes):
                    mids = [f.inputs["X"][0], f.outputs["Out"][0]]
                    if any(n in protected for n in mids):
                        ok = False
                    if len(cons.get(f.outputs["Out"][0], [])) != 1 or \
                            len(cons.get(f.inputs["X"][0], [])) != 1:
                        ok = False
                    # XShape side outputs must be dead
                    for side in (f.outputs.get("XShape", [])
                                 + t.outputs.get("XShape", [])):
                        if cons.get(side, []):
                            ok = False
                if not ok:
                    continue
                xs = [t.inputs["X"][0] for t in transposes]
                idx = block.ops.index(cat)
                dead = flats + transposes
                idx -= sum(1 for d in dead if block.ops.index(d) < idx)
                remove_ops(block, dead + [cat])
                block._insert_op(
                    idx, "fusion_transpose_flatten_concat",
                    inputs={"X": xs},
                    outputs={"Out": list(cat.outputs["Out"])},
                    attrs={"trans_axis": list(next(iter(perms))),
                           "flatten_axis": next(iter(faxes)),
                           "concat_axis": int(cat.attrs.get("axis", 0))})
                fused += 1
                changed = True
                break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


# --------------------------------------------------------------------------
# fused optimizer shell (reference: ir/fuse_optimizer_ops_pass/ —
# fuse_sgd_op_pass.cc, fuse_momentum_op_pass.cc, fuse_adam_op_pass.cc):
# merge per-parameter update ops sharing one LR var and hyperparams into
# a single multi-slot fused op.
# --------------------------------------------------------------------------
_FUSABLE_OPT = {
    "sgd": (("Param", "Grad"), ("ParamOut",)),
    "momentum": (("Param", "Grad", "Velocity"), ("ParamOut", "VelocityOut")),
    "adam": (("Param", "Grad", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow"),
             ("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
              "Beta2PowOut")),
}


@register_pass("squared_mat_sub_fuse_pass")
class SquaredMatSubFusePass(Pass):
    """matmul(x,y)^2 - matmul(x^2,y^2) [* scalar]  ==>
    fusion_squared_mat_sub (reference: ir/squared_mat_sub_fuse_pass.cc
    building operators/fused/fusion_squared_mat_sub_op.cc — the sim-net
    second-order feature cross).  Inference-shape rewrite."""

    protected: Sequence[str] = ()

    def apply_impl(self, program):
        fused = 0
        block = program.global_block()
        protected = set(self.protected)
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            prod = producer_map(block)
            for sub in list(block.ops):
                if sub.type != "elementwise_sub":
                    continue
                sq_xy = prod.get(sub.inputs["X"][0])
                mm_sq = prod.get(sub.inputs["Y"][0])
                if (sq_xy is None or mm_sq is None
                        or sq_xy.type != "square"
                        or mm_sq.type != "matmul"):
                    continue
                mm_xy = prod.get(sq_xy.inputs["X"][0])
                if mm_xy is None or mm_xy.type != "matmul":
                    continue
                sq_x = prod.get(mm_sq.inputs["X"][0])
                sq_y = prod.get(mm_sq.inputs["Y"][0])
                if (sq_x is None or sq_y is None or sq_x.type != "square"
                        or sq_y.type != "square"):
                    continue
                if (sq_x.inputs["X"][0] != mm_xy.inputs["X"][0]
                        or sq_y.inputs["X"][0] != mm_xy.inputs["Y"][0]):
                    continue
                if any(mm.attrs.get(k, False) for mm in (mm_xy, mm_sq)
                       for k in ("transpose_X", "transpose_Y")):
                    continue
                if any(mm.attrs.get("alpha", 1.0) != 1.0
                       for mm in (mm_xy, mm_sq)):
                    continue  # alpha scaling is not part of the fused op
                inner = [mm_xy.outputs["Out"][0], sq_xy.outputs["Out"][0],
                         sq_x.outputs["Out"][0], sq_y.outputs["Out"][0],
                         mm_sq.outputs["Out"][0]]
                if any(len(cons.get(n, [])) != 1 or n in protected
                       for n in inner):
                    continue
                out_name = sub.outputs["Out"][0]
                dead = [mm_xy, sq_xy, sq_x, sq_y, mm_sq, sub]
                scalar = 1.0
                users = cons.get(out_name, [])
                if (out_name not in protected and len(users) == 1
                        and users[0].type == "scale"
                        and users[0].attrs.get("bias", 0.0) == 0.0
                        and not users[0].inputs.get("ScaleTensor")):
                    scalar = float(users[0].attrs.get("scale", 1.0))
                    out_name = users[0].outputs["Out"][0]
                    dead.append(users[0])
                # earliest dead op's slot keeps topological order (the
                # square(x)/square(y) ops may precede the matmul)
                idx = min(block.ops.index(o) for o in dead)
                x_in, y_in = list(mm_xy.inputs["X"]), list(mm_xy.inputs["Y"])
                remove_ops(block, dead)
                block._insert_op(
                    idx, "fusion_squared_mat_sub",
                    inputs={"X": x_in, "Y": y_in},
                    outputs={"Out": [out_name]},
                    attrs={"scalar": scalar})
                fused += 1
                changed = True
                break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


@register_pass("repeated_fc_relu_fuse_pass")
class RepeatedFcReluFusePass(Pass):
    """N>=2 chained fc(relu) ops ==> fusion_repeated_fc_relu
    (reference: ir/repeated_fc_relu_fuse_pass.cc). Run AFTER
    fc_fuse_pass so the chain is already in fc form."""

    protected: Sequence[str] = ()

    def apply_impl(self, program):
        fused = 0
        block = program.global_block()
        protected = set(self.protected)
        changed = True
        while changed:
            changed = False
            cons = _consumers(block)
            prod = producer_map(block)

            def is_relu_fc(op_):
                return (op_ is not None and op_.type == "fc"
                        and op_.attrs.get("activation_type") == "relu"
                        and op_.attrs.get("in_num_col_dims", 1) == 1
                        and bool(op_.inputs.get("Bias")))  # fc bias optional

            for head in list(block.ops):
                if not is_relu_fc(head):
                    continue
                # head must START a chain: its input not from a relu-fc
                if is_relu_fc(prod.get(head.inputs["Input"][0])):
                    continue
                chain = [head]
                while True:
                    o = chain[-1].outputs["Out"][0]
                    users = cons.get(o, [])
                    if (o in protected or len(users) != 1
                            or not is_relu_fc(users[0])
                            or users[0].inputs["Input"][0] != o):
                        break
                    chain.append(users[0])
                if len(chain) < 2:
                    continue
                idx = block.ops.index(head)
                inputs = {"X": list(head.inputs["Input"]),
                          "W": [fc.inputs["W"][0] for fc in chain],
                          "Bias": [fc.inputs["Bias"][0] for fc in chain]}
                out_name = chain[-1].outputs["Out"][0]
                remove_ops(block, chain)
                block._insert_op(
                    idx, "fusion_repeated_fc_relu", inputs=inputs,
                    outputs={"Out": [out_name]}, attrs={})
                fused += 1
                changed = True
                break
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


# --------------------------------------------------------------------------
# NHWC layout propagation (reference intent: the transfer_layout logic in
# ir/layout transform + MLPerf-on-TPU channels-last recipes, arxiv
# 1909.09756 §4).  Paddle programs are built NCHW; the TPU's native conv
# layout is channels-last.  This pass walks the (already-differentiated)
# global block once and rewrites conv/bn/pool chains — forward AND grad
# ops — to compute in NHWC:
#
# * layout-preferring ops (conv2d/pool2d/batch_norm/fused bn-act, and
#   their grad ops) get data_format/data_layout = "NHWC" and their 4-D
#   data inputs/outputs renamed to `name@NHWC` alias vars;
# * layout-agnostic elementwise ops (relu/cast/sum/elementwise_add and
#   grads) ride along in NHWC when all their data inputs already are;
# * a transpose2 is inserted ONLY at subgraph boundaries: NCHW->NHWC
#   lazily on first NHWC use of an NCHW value, NHWC->NCHW lazily on
#   first NCHW use of an NHWC value.  Alias reuse makes adjacent
#   transpose pairs cancel by construction — a value transposed once is
#   never re-transposed, so an unbroken conv->bn->relu->conv chain has
#   exactly one transpose in and one out.
#
# Filters stay OIHW: the conv lowering passes NHWC dimension numbers to
# lax.conv_general_dilated with an OIHW rhs spec, so weights (and their
# grads, and the optimizer state) keep their NCHW-era layout — flipping
# FLAGS_tpu_nhwc mid-training is safe.
# --------------------------------------------------------------------------
_NHWC_SUFFIX = "@NHWC"

#: op type -> (layout attr, data input slots, data output slots).  Slots
#: not listed (Filter, Scale, running stats, ...) are per-channel or
#: kernel-layout values the NHWC lowering consumes unchanged.
_LAYOUT_OPS: Dict[str, tuple] = {
    "conv2d": ("data_format", ("Input",), ("Output",)),
    "depthwise_conv2d": ("data_format", ("Input",), ("Output",)),
    "conv2d_grad": ("data_format", ("Input", "Output", "Output@GRAD"),
                    ("Input@GRAD",)),
    "depthwise_conv2d_grad": ("data_format",
                              ("Input", "Output", "Output@GRAD"),
                              ("Input@GRAD",)),
    "pool2d": ("data_format", ("X",), ("Out",)),
    "pool2d_grad": ("data_format", ("X", "Out", "Out@GRAD"), ("X@GRAD",)),
    "batch_norm": ("data_layout", ("X",), ("Y",)),
    "batch_norm_grad": ("data_layout", ("X", "Y", "Y@GRAD"), ("X@GRAD",)),
    "fused_batch_norm_act": ("data_layout", ("X",), ("Y",)),
    "fused_batch_norm_act_grad": ("data_layout", ("X", "Y", "Y@GRAD"),
                                  ("X@GRAD",)),
    "fused_bn_add_activation": ("data_layout", ("X", "Z"), ("Y",)),
    "fused_bn_add_activation_grad": ("data_layout", ("X", "Y", "Y@GRAD"),
                                     ("X@GRAD", "Z@GRAD")),
    # r14 fused conv epilogues: ONE layout attr (data_format) governs
    # conv and BN; Filter/Filter@GRAD stay OIHW in both layouts
    "fused_conv_bn_act": ("data_format", ("Input", "Z"),
                          ("Output", "ConvOut")),
    "fused_conv_bn_act_grad": ("data_format",
                               ("Input", "ConvOut", "Output",
                                "Output@GRAD"),
                               ("Input@GRAD", "Z@GRAD")),
}

#: elementwise ops that compute identically in any layout: converted to
#: consume/produce NHWC aliases when every 4-D data input already has
#: one, so they never force a transpose back to NCHW mid-chain.
_LAYOUT_AGNOSTIC: Dict[str, tuple] = {
    "relu": (("X",), ("Out",)),
    "relu_grad": (("X", "Out", "Out@GRAD"), ("X@GRAD",)),
    "cast": (("X",), ("Out",)),
    "cast_grad": (("X", "Out", "Out@GRAD"), ("X@GRAD",)),
    "elementwise_add": (("X", "Y"), ("Out",)),
    "elementwise_add_grad": (("X", "Y", "Out", "Out@GRAD"),
                             ("X@GRAD", "Y@GRAD")),
    "sum": (("X",), ("Out",)),
}


@register_pass("layout_transform_pass")
class LayoutTransformPass(Pass):
    """NCHW -> NHWC propagation over conv/bn/pool/elementwise chains."""

    #: var names whose NCHW value must stay addressable (fetch targets)
    protected: Sequence[str] = ()

    def apply_impl(self, program):
        block = program.global_block()
        keep_nchw = set(self.protected)
        # names referenced from other blocks (while/cond bodies) must
        # keep their NCHW binding — sub-blocks are not rewritten
        for other in program.blocks:
            if other is block:
                continue
            for op_ in other.ops:
                for names in op_.inputs.values():
                    keep_nchw.update(names)
                for names in op_.outputs.values():
                    keep_nchw.update(names)
        self.converted_count = self._apply_block(block, keep_nchw)
        if self.converted_count:
            program._bump_version()
        return program

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _is_4d(block, name):
        if not name or name == "@EMPTY@":
            return False
        v = block._find_var_recursive(name)
        return v is not None and v.shape is not None and len(v.shape) == 4

    def _eligible(self, op_, block, attr_name, din, dout):
        if op_.attrs.get(attr_name, "NCHW") not in ("NCHW", "AnyLayout"):
            return False
        if op_.type.startswith("pool2d"):
            if op_.attrs.get("adaptive", False) and \
                    not op_.attrs.get("global_pooling", False):
                return False  # NHWC adaptive: only the lowering's
                #                divisible path; stay conservative
        names = []
        for slot in din:
            names.extend(op_.inputs.get(slot, []))
        for slot in dout:
            names.extend(n for n in op_.outputs.get(slot, [])
                         if n != "@EMPTY@")
        if not names:
            return False
        return all(self._is_4d(block, n) for n in names
                   if n != "@EMPTY@")

    # -- main walk ---------------------------------------------------------
    def _apply_block(self, block, keep_nchw):
        converted = 0
        new_ops: List[Operator] = []
        alias: Dict[str, str] = {}   # NCHW name -> live NHWC alias
        pending: set = set()         # names whose NCHW value is not
        #                              materialized (only alias is live)

        def alias_var(name):
            aname = name + _NHWC_SUFFIX
            if not block.has_var(aname):
                v = block._find_var_recursive(name)
                s = list(v.shape)
                block.create_var(name=aname,
                                 shape=(s[0], s[2], s[3], s[1]),
                                 dtype=v.dtype)
            return aname

        def to_nhwc(name):
            a = alias.get(name)
            if a is not None:
                return a
            a = alias_var(name)
            new_ops.append(Operator(
                block, "transpose2", inputs={"X": [name]},
                outputs={"Out": [a]}, attrs={"axis": [0, 2, 3, 1]}))
            alias[name] = a
            return a

        def to_nchw(name):
            if name in pending:
                new_ops.append(Operator(
                    block, "transpose2", inputs={"X": [alias[name]]},
                    outputs={"Out": [name]}, attrs={"axis": [0, 3, 1, 2]}))
                pending.discard(name)
            return name

        def invalidate_outputs(op_, except_slots=()):
            """An op overwriting an aliased name makes the alias stale."""
            for slot, names in op_.outputs.items():
                if slot in except_slots:
                    continue
                for n in names:
                    if n in alias:
                        alias.pop(n, None)
                        pending.discard(n)

        def convert(op_, attr_name, din, dout):
            """Rewrite one op to compute in NHWC: data input slots take
            (or create) aliases, data output slots produce aliases, the
            layout attr flips — including the __fwd_attrs__ snapshot the
            vjp replay of grad ops reads."""
            data_out_names = {n for slot in dout
                              for n in op_.outputs.get(slot, [])}
            # non-data input slots are per-channel/kernel values that
            # should never be pending; stay safe if one is
            for slot, names in list(op_.inputs.items()):
                if slot in din:
                    op_.inputs[slot] = [
                        to_nhwc(n) if n != "@EMPTY@" else n for n in names]
                else:
                    for n in names:
                        if n in pending:
                            to_nchw(n)
            invalidate_outputs(op_, except_slots=dout)
            for slot in dout:
                names = op_.outputs.get(slot, [])
                rewritten = []
                for n in names:
                    if n == "@EMPTY@":
                        rewritten.append(n)
                        continue
                    a = alias_var(n)
                    alias[n] = a
                    pending.add(n)
                    rewritten.append(a)
                if names:
                    op_.outputs[slot] = rewritten
            if attr_name is not None:
                op_.attrs[attr_name] = "NHWC"
                fa = op_.attrs.get("__fwd_attrs__")
                if isinstance(fa, dict):
                    fa = dict(fa)
                    fa[attr_name] = "NHWC"
                    op_.attrs["__fwd_attrs__"] = fa
            new_ops.append(op_)
            # fetch targets / persistables need their NCHW value live NOW
            for n in data_out_names:
                if n != "@EMPTY@" and n in pending:
                    v = block._find_var_recursive(n)
                    if n in keep_nchw or (v is not None and
                                          getattr(v, "persistable", False)):
                        to_nchw(n)

        for op_ in list(block.ops):
            spec = _LAYOUT_OPS.get(op_.type)
            agn = _LAYOUT_AGNOSTIC.get(op_.type)
            if spec is not None:
                attr_name, din, dout = spec
                if self._eligible(op_, block, din=din, dout=dout,
                                  attr_name=attr_name):
                    convert(op_, attr_name, din, dout)
                    converted += 1
                    continue
            elif agn is not None and self._agnostic_ok(op_, block, alias,
                                                       *agn):
                din, dout = agn
                convert(op_, None, din, dout)
                converted += 1
                continue
            # generic op: consume NCHW — materialize any pending input
            for names in op_.inputs.values():
                for n in names:
                    if n in pending:
                        to_nchw(n)
            invalidate_outputs(op_)
            new_ops.append(op_)

        # live-out NHWC values someone outside the block may read
        for n in sorted(pending):
            v = block._find_var_recursive(n)
            if n in keep_nchw or (v is not None
                                  and getattr(v, "persistable", False)):
                to_nchw(n)
        if converted:
            block.ops[:] = new_ops
        return converted

    def _agnostic_ok(self, op_, block, alias, din, dout):
        """Every 4-D data input must already be NHWC; elementwise_add
        additionally needs the default axis and equal shapes (a
        broadcasting add is layout-sensitive)."""
        names_in = [n for slot in din for n in op_.inputs.get(slot, [])
                    if n != "@EMPTY@"]
        names_out = [n for slot in dout for n in op_.outputs.get(slot, [])
                     if n != "@EMPTY@"]
        if not names_in or not names_out:
            return False
        if not all(self._is_4d(block, n) for n in names_in + names_out):
            return False
        if not all(n in alias for n in names_in):
            return False
        if op_.type.startswith("elementwise_add"):
            if op_.attrs.get("axis", -1) != -1:
                return False
            shapes = {tuple(block._find_var_recursive(n).shape)
                      for n in names_in}
            if len(shapes) != 1:
                return False
        return True

# --------------------------------------------------------------------------
# coalesced gradient communication (reference: ir/fuse_all_reduce_op_pass.cc
# + coalesce_grad_tensor_pass.cc): the per-tensor c_allreduce_sum ops a
# GradAllReduce transpile inserts each pay a collective launch; bucketing
# ~FLAGS_fuse_grad_size_in_MB of payload into one flattened collective
# amortizes the launches and gives XLA one large transfer to overlap with
# the remaining backward compute.
# --------------------------------------------------------------------------
@register_pass("fuse_all_reduce_pass")
class FuseAllReducePass(Pass):
    """Bucket in-place `c_allreduce_sum` ops into `c_fused_allreduce`
    (`c_fused_reduce_scatter` under ZeRO-2 — see ``sharding_stage``).

    Merge rules (each violation closes the current bucket):
    * only in-place (X == Out) sum-allreduces with static shapes and no
      `use_mean` are eligible;
    * members share one (ring_id, dtype, scatter-eligibility) —
      mixed-dtype buckets refuse to merge;
    * an intervening op that reads or writes a bucketed var closes the
      bucket first (the fused collective runs at the LAST member's
      position, so nothing may consume an unreduced value in between);
    * a bucket closes once its payload reaches ``max_bytes`` (so every
      full bucket carries >= max_bytes and the bucket count on an
      N-tensor program is <= ceil(total_bytes / max_bytes));
    * single-member buckets keep their original op — nothing to fuse.

    ``overlap=True`` (FLAGS_dp_comm_overlap, reference:
    multi_devices_graph_pass backward-op-aware allreduce ordering)
    additionally schedules the comm for backward overlap: buckets form
    in *last-gradient-ready* order instead of program-tail order, and
    each bucket's collective (plus its private in-place prologue, e.g.
    the 1/nranks scale) moves to just after the last op producing any
    of its inputs — so bucket 0's collective is in flight while later
    layers are still in backward, and on the pjit path the collective
    ops land interleaved into the backward op list where XLA's async
    collectives can overlap them.  Placement safety: every op touching
    a member var before its reduce sits at or before the bucket's
    anchor (the anchor IS the last such toucher), so no op changes the
    value it observes.

    ``sharding_stage >= 2`` with ``ndev > 1`` (ZeRO-2,
    FLAGS_dp_sharding): buckets whose every grad feeds a shard-eligible
    optimizer update lower to ``c_fused_reduce_scatter`` — each device
    receives only its 1/ndev row-shard of every reduced grad, which the
    DP runner's shard-aware update consumes directly (no full-gradient
    materialization; wire bytes halve vs allreduce).

    ``autotune=True`` (FLAGS_fuse_grad_size_in_MB="auto", r9): instead
    of the fixed byte threshold, bucket boundaries come from the
    modeled backward timeline (utils/cost_model.py).  An O(N^2) DP over
    the ready-ordered entries picks the contiguous partition minimizing
    the finish time of the serialized collective stream — each bucket's
    collective (ring alpha-beta model) should complete about when the
    next bucket's last gradient is ready, so est. exposed comm is
    minimized rather than bucket count.  Same-key contiguity and the
    ``placeable`` anchor-safety rule still bound every bucket; a
    numeric flag value restores the fixed threshold bit-for-bit.
    """

    max_bytes: int = 32 << 20
    compress: str = "none"
    overlap: bool = False
    sharding_stage: int = 0
    ndev: int = 1
    autotune: bool = False
    cost_model = None  # utils.cost_model.CostModel override (tests/CLI)

    def _payload_bytes(self, block, name):
        import numpy as np

        from .dtype import to_numpy_dtype

        var = block._find_var_recursive(name)
        if var is None or var.shape is None or var.dtype is None:
            return None
        shape = list(var.shape)
        if not shape or any(d is None or d < 0 for d in shape):
            return None
        try:
            itemsize = np.dtype(to_numpy_dtype(var.dtype)).itemsize
        except Exception:
            return None
        return int(np.prod(shape)) * itemsize, var.dtype

    # -- ZeRO-2 eligibility ------------------------------------------------
    def _scatter_names(self, block):
        """Grad names safe to reduce-scatter: every post-reduce consumer
        is either the (shard-eligible) optimizer update the DP runner
        wraps, or a no-op sync — anything else would read a 1/ndev
        shard where it expects the full tensor."""
        if int(self.sharding_stage) < 2 or int(self.ndev) <= 1:
            return set()
        from ..parallel.data_parallel import _update_shard_rows

        sync_ops = {"c_sync_comm_stream", "c_sync_calc_stream",
                    "c_wait_comm_stream", "c_wait_calc_stream", "barrier"}
        ok = set()
        consumers: Dict[str, List[Operator]] = {}
        for op_ in block.ops:
            for n in set(op_.input_arg_names):
                consumers.setdefault(n, []).append(op_)
        for op_ in block.ops:
            if op_.type != "c_allreduce_sum":
                continue
            g = op_.inputs.get("X", [None])[0]
            if not g:
                continue
            update = None
            safe = True
            seen_reduce = False
            for c in consumers.get(g, []):
                if c is op_:
                    seen_reduce = True
                    continue
                if not seen_reduce:
                    continue  # pre-reduce readers see the full local grad
                if c.type in sync_ops:
                    continue
                if (update is None
                        and _update_shard_rows(c, block, int(self.ndev))
                        and g in c.inputs.get("Grad", [])):
                    update = c
                    continue
                safe = False
                break
            if safe and update is not None:
                ok.add(g)
        return ok

    def _bucket_attrs(self, block, members):
        xs = [e["x"] for e in members]
        # the compress attr records the format that actually ships:
        # the lowering only compresses f32 payloads, so stamping
        # bf16 on another dtype would mislead comm accounting
        dtype = members[0]["dtype"]
        compress = self.compress if dtype == VarType.FP32 else "none"
        attrs = {"ring_id": members[0]["ring"], "compress": compress}
        if "op_role" in members[0]["op"].attrs:
            attrs["op_role"] = members[0]["op"].attrs["op_role"]
        return xs, attrs

    def apply_impl(self, program):
        self.fused_count = 0
        if self.max_bytes <= 0:
            return program
        block = program.global_block()
        scatter_names = self._scatter_names(block)
        if self.overlap:
            changed = self._apply_overlap(block, scatter_names)
        else:
            changed = self._apply_append(block, scatter_names)
        if changed:
            program._bump_version()
        return program

    # -- r7 schedule: fuse in program order, issue at last member ----------
    def _apply_append(self, block, scatter_names):
        buckets: List[List[dict]] = []
        cur: List[dict] = []
        cur_bytes = 0
        cur_key = None
        touched: set = set()

        def close():
            nonlocal cur, cur_bytes, cur_key
            if len(cur) >= 2:
                buckets.append(list(cur))
            cur, cur_bytes, cur_key = [], 0, None
            touched.clear()

        for op_ in list(block.ops):
            if (op_.type == "c_allreduce_sum"
                    and not op_.attrs.get("use_mean", False)):
                x = op_.inputs.get("X", [None])[0]
                o = op_.outputs.get("Out", [None])[0]
                info = self._payload_bytes(block, x) if x else None
                if x is None or x != o or info is None:
                    close()
                    continue
                nbytes, dtype = info
                key = (op_.attrs.get("ring_id", 0), dtype,
                       x in scatter_names)
                if cur and (key != cur_key or x in touched):
                    close()
                cur.append({"op": op_, "x": x, "dtype": dtype,
                            "ring": op_.attrs.get("ring_id", 0)})
                cur_bytes += nbytes
                cur_key = key
                touched.add(x)
                if cur_bytes >= self.max_bytes:
                    close()
                continue
            names = set(op_.input_arg_names) | set(op_.output_arg_names)
            if names & touched:
                close()
        close()

        for b in buckets:
            xs, attrs = self._bucket_attrs(block, b)
            fused_type = ("c_fused_reduce_scatter"
                          if b[0]["x"] in scatter_names
                          else "c_fused_allreduce")
            ops_ = [e["op"] for e in b]
            last = max(block.ops.index(o) for o in ops_)
            last -= sum(1 for o in ops_ if block.ops.index(o) < last)
            remove_ops(block, ops_)
            block._insert_op(last, fused_type,
                             inputs={"X": xs}, outputs={"Out": list(xs)},
                             attrs=attrs)
        self.fused_count = len(buckets)
        return bool(buckets)

    # -- overlap schedule: ready-order buckets, issue at last producer -----
    def _collect_entries(self, block, scatter_names):
        ops = list(block.ops)
        seen_reduce: Dict[str, int] = {}
        entries = []
        for i, op_ in enumerate(ops):
            if (op_.type != "c_allreduce_sum"
                    or op_.attrs.get("use_mean", False)):
                continue
            x = op_.inputs.get("X", [None])[0]
            o = op_.outputs.get("Out", [None])[0]
            info = self._payload_bytes(block, x) if x else None
            if x is None or x != o or info is None:
                continue
            if x in seen_reduce:
                # two reduces of one var: scheduling either would reorder
                # them — leave both in place
                seen_reduce[x] = -1
                continue
            seen_reduce[x] = len(entries)
            nbytes, dtype = info
            # walk back over the private in-place prologue (the
            # transpiler's 1/nranks scale): ops touching ONLY x move
            # with the collective; the first other toucher is the
            # anchor this bucket may not be issued before.
            chain: List[int] = []
            anchor = -1
            j = i - 1
            while j >= 0:
                o2 = ops[j]
                names = set(o2.input_arg_names) | set(o2.output_arg_names)
                if x in names:
                    if names <= {x} and x in o2.output_arg_names:
                        chain.append(j)
                        j -= 1
                        continue
                    anchor = j
                    break
                j -= 1
            chain.reverse()
            entries.append({"op": op_, "idx": i, "x": x, "nbytes": nbytes,
                            "dtype": dtype,
                            "ring": op_.attrs.get("ring_id", 0),
                            "chain": chain, "anchor": anchor})
        return [e for e in entries
                if seen_reduce.get(e["x"]) != -1], ops

    def _apply_overlap(self, block, scatter_names):
        entries, ops = self._collect_entries(block, scatter_names)
        if not entries:
            self.fused_count = 0
            return False
        entries.sort(key=lambda e: (e["anchor"], e["idx"]))

        touch: Dict[str, List[int]] = {}
        for i, o in enumerate(ops):
            for n in set(o.input_arg_names) | set(o.output_arg_names):
                touch.setdefault(n, []).append(i)

        def placeable(members, anchor):
            """A bucket issues after `anchor` (original index).  Every
            pre-reduce toucher of a member sits at or before its own
            anchor <= `anchor`, so those stay correct by construction —
            but a POST-reduce consumer of a member whose own reduce sat
            before `anchor` (e.g. the hierarchical all-gather between
            two shard allreduces) would now run before the moved
            collective and read an unreduced value: refuse."""
            for e in members:
                own = set(e["chain"])
                own.add(e["idx"])
                for j in touch.get(e["x"], []):
                    if j not in own and e["idx"] < j <= anchor:
                        return False
            return True

        def placement_horizon(e):
            """Last original index a bucket containing `e` may anchor
            at: one before e's first post-reduce toucher (the same rule
            placeable scans for) — inf when no such toucher exists.
            Precomputed once so the autotune DP checks a split in O(1)
            (running max anchor vs running min horizon) instead of
            rescanning every member's touch list per (i, j) pair."""
            own = set(e["chain"])
            own.add(e["idx"])
            h = float("inf")
            for j in touch.get(e["x"], []):
                if j not in own and j > e["idx"]:
                    h = min(h, j - 1)
            return h

        buckets: List[List[dict]] = None
        if self.autotune:
            buckets = self._autotune_buckets(
                entries, ops, block,
                [placement_horizon(e) for e in entries], scatter_names)
        if buckets is None:
            buckets = []
            cur: List[dict] = []
            cur_bytes = 0
            cur_key = None
            for e in entries:
                key = (e["ring"], e["dtype"], e["x"] in scatter_names)
                if cur and (key != cur_key or not placeable(
                        cur + [e], max(m["anchor"] for m in cur + [e]))):
                    buckets.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(e)
                cur_bytes += e["nbytes"]
                cur_key = key
                if cur_bytes >= self.max_bytes:
                    buckets.append(cur)
                    cur, cur_bytes, cur_key = [], 0, None
            if cur:
                buckets.append(cur)

        moved: set = set()
        schedule: Dict[int, List[List[Operator]]] = {}
        fused = 0
        for b in buckets:  # already in ready (issue) order
            anchor = max(e["anchor"] for e in b)
            emit: List[Operator] = []
            for e in b:
                emit.extend(ops[j] for j in e["chain"])
                moved.update(e["chain"])
                moved.add(e["idx"])
            if len(b) == 1:
                emit.append(b[0]["op"])  # nothing to fuse: op kept, moved
            else:
                xs, attrs = self._bucket_attrs(block, b)
                fused_type = ("c_fused_reduce_scatter"
                              if b[0]["x"] in scatter_names
                              else "c_fused_allreduce")
                emit.append(Operator(block, fused_type,
                                     inputs={"X": xs},
                                     outputs={"Out": list(xs)},
                                     attrs=attrs))
                fused += 1
            schedule.setdefault(anchor, []).append(emit)

        out: List[Operator] = []
        for emit in schedule.get(-1, []):
            out.extend(emit)
        for i, op_ in enumerate(ops):
            if i in moved:
                continue
            out.append(op_)
            for emit in schedule.get(i, []):
                out.extend(emit)
        block.ops[:] = out
        self.fused_count = fused
        return True

    # -- measurement-driven bucket boundaries (r9 autotune) ----------------
    def _autotune_buckets(self, entries, ops, block, horizons,
                          scatter_names):
        """Partition the ready-ordered entries into variable buckets by
        minimizing the modeled finish time of the serialized collective
        stream (utils/cost_model.py).  finish(partition) determines the
        exposed tail past the backward horizon, so minimizing finish
        minimizes est. exposed comm.  DP over contiguous splits:
        best[i] = min over j of max(best[j], ready[i-1]) + comm(j..i),
        restricted to same-key, placement-safe buckets.  Returns None
        (caller falls back to the fixed-threshold greedy) when no valid
        partition exists."""
        from ..utils.cost_model import (backward_timeline,
                                        collective_time_s,
                                        default_cost_model)

        if not entries:
            return None
        # no explicit override: start from the measured profile when the
        # profiler has recorded one (r13 calibration loop) — the same
        # rates tools/dp_comm_stats models with
        cm = self.cost_model or default_cost_model(ops, block)
        times, _ = backward_timeline(ops, block, cm)
        ready = [times[e["anchor"]] if e["anchor"] >= 0 else 0.0
                 for e in entries]
        keys = [(e["ring"], e["dtype"], e["x"] in scatter_names)
                for e in entries]
        nranks = max(int(self.ndev), 1)
        N = len(entries)
        INF = float("inf")
        best = [INF] * (N + 1)
        best[0] = 0.0
        cut = [0] * (N + 1)
        for i in range(1, N + 1):
            nbytes = 0
            anc = -1
            safe = INF
            for j in range(i - 1, -1, -1):
                if keys[j] != keys[i - 1]:
                    break  # buckets are same-key contiguous runs
                nbytes += entries[j]["nbytes"]
                # bucket [j:i) anchors at its max member anchor; safe
                # iff that never passes any member's placement horizon
                anc = max(anc, entries[j]["anchor"])
                safe = min(safe, horizons[j])
                if best[j] == INF or anc > safe:
                    continue
                factor = 1.0 if keys[j][2] else 2.0
                comm = collective_time_s(nbytes, factor, nranks, cm)
                fin = max(best[j], ready[i - 1]) + comm
                if fin < best[i]:
                    best[i] = fin
                    cut[i] = j
        if best[N] == INF:
            return None
        bounds = []
        i = N
        while i > 0:
            bounds.append((cut[i], i))
            i = cut[i]
        bounds.reverse()
        return [entries[a:b] for a, b in bounds]


@register_pass("prefetch_autotune_pass")
class PrefetchAutotunePass(Pass):
    """Per-parameter ZeRO-3 prefetch-depth autotune (r16, the ROADMAP
    carry-over): instead of one FLAGS_dp_prefetch_depth for every
    parameter, derive each sharded parameter's window depth from the
    cost model — just deep enough that the modeled all-gather time is
    hidden behind the compute ops preceding its first consumer
    (utils/cost_model.py ``collective_time_s`` vs accumulated
    ``op_time_s``, profile-calibrated when a measured step exists).

    This is an ANALYSIS pass: it mutates nothing (the op-motion itself
    stays in the DP interpreter, driven by
    ``data_parallel._plan_param_prefetch(depths=...)``), but it runs
    through ``Pass.apply`` so the r10 verifier bracket covers it like
    every pass, and the windows it produces are re-validated by the
    verifier's ``check_prefetch_plan`` gather-window-never-crosses-a-
    param-write rule on the DP compile path.  Results land in
    ``self.report``: ``depths`` (param -> depth) and the planned
    ``records``.  Consumed by parallel/plan_search.py's ``auto``
    prefetch candidates."""

    ndev: int = 1
    use_shard_map: bool = False
    max_depth: int = 8
    cost_model = None  # utils.cost_model.CostModel override (tests/CLI)

    def apply_impl(self, program):
        from ..parallel.data_parallel import (_pjit_zero23_sets,
                                              _plan_param_prefetch,
                                              _plan_wrapped_updates)
        from ..utils.cost_model import (COMM_OPS, collective_time_s,
                                        default_cost_model, op_time_s)

        block = program.global_block()
        ops = list(block.ops)
        ndev = max(int(self.ndev), 1)
        if self.use_shard_map:
            plans, _, sharded = _plan_wrapped_updates(ops, block, ndev, 3)
            skip = set(plans)
        else:
            sharded, _ = _pjit_zero23_sets(ops, block, ndev, 3)
            skip = set()
        self.report = {"depths": {}, "records": [], "ndev": ndev}
        if not sharded or ndev <= 1:
            return program
        cm = self.cost_model or default_cost_model(ops, block)
        op_s = [0.0 if op_.type in COMM_OPS else op_time_s(op_, block, cm)
                for op_ in ops]
        from ..framework import memory_plan as _mp

        first_use: Dict[str, int] = {}
        for i, op_ in enumerate(ops):
            if id(op_) in skip:
                continue
            for n in set(op_.input_arg_names):
                if n in sharded:
                    first_use.setdefault(n, i)
        depths: Dict[str, int] = {}
        for p in sorted(sharded):
            b = _mp.var_bytes(block, p) or 0
            gather_s = collective_time_s(float(b), 1.0, ndev, cm)
            f = first_use.get(p, 0)
            acc, d, i = 0.0, 0, f - 1
            while i >= 0 and d < int(self.max_depth) and acc < gather_s:
                acc += op_s[i]
                d += 1
                i -= 1
            depths[p] = max(d, 1)
        records, _, _ = _plan_param_prefetch(ops, block, sharded, skip,
                                             1, depths=depths)
        self.report = {"depths": depths, "records": records, "ndev": ndev}
        return program


# --------------------------------------------------------------------------
# numerics probe (r20) — the observability mirror of the fusion passes:
# instead of rewriting compute, append cheap stat reductions over
# selected op outputs so every step fetches ONE packed vector of per-var
# health partials (framework/numerics.py finalizes and consumes them).
# Existing registered ops only (cast/abs/square/reduce_max/reduce_sum/
# isfinite_v2/size/stack + c_allreduce_{max,sum} for cross-shard
# combines), so the pass adds no op-sweep surface.
# --------------------------------------------------------------------------
@register_pass("numerics_probe_pass")
class NumericsProbePass(Pass):
    """Append in-program tensor-stat probes (FLAGS_numerics_probe).

    For every selected var (grad/param/update-role always, plus outputs
    of ops matching ``ops_regex`` — see
    ``numerics.select_probe_targets``) the pass emits five partial
    reductions in f32 — absmax, sum, sum-of-squares, finite-count,
    numel — and packs all of them into one ``@numerics_stats@`` vector
    via a single ``stack`` op.  Probes read FINAL values (appended
    after every producer), so their order is the program order of each
    var's last writer — the first-divergence order
    tools/bisect_divergence.py reports in.

    On the shard_map DP path (the program carries ``c_*`` ops) each
    partial of a *shard-variant* var — batch-sharded activation,
    ZeRO-sharded optimizer state, reduce-scattered grad — is combined
    across shards with ``c_allreduce_max`` / ``c_allreduce_sum`` (the
    ``cross_shard_norms`` trick), so finalized stats are layout-,
    ZeRO-stage- and DP-path-invariant; replicated values are combined
    with nothing (a psum would multiply them by ndev).  Outside a mesh
    the combines are identity, so the probed program still runs
    anywhere.

    Probe ops carry ``op_role=Optimize``: they consume ZeRO-3 params as
    shard-or-gathered values like update ops do, keeping them out of
    the prefetch planner's consumer windows (a forward-role read at the
    block end would drag every gather window across the param's update
    write — exactly what the verifier's window rule forbids)."""

    ops_regex: str = ""

    _COMBINE = {"absmax": "c_allreduce_max", "sum": "c_allreduce_sum",
                "sumsq": "c_allreduce_sum", "nonfinite": "c_allreduce_sum",
                "numel": "c_allreduce_sum"}

    def apply_impl(self, program):
        from . import numerics
        from ..backward import OP_ROLE_KEY, OpRole

        block = program.global_block()
        if block.has_var(numerics.STATS_VAR):
            program._numerics_layout = getattr(program,
                                               "_numerics_layout", None)
            return program  # already probed (pass is idempotent)
        targets = numerics.select_probe_targets(program, block,
                                                self.ops_regex)
        self.report = {"targets": targets}
        program._numerics_layout = None
        if not targets:
            return program
        # shard-variance via the shared distribution-state engine
        # (framework/shard_analysis.py — r26 replaced the pass's private
        # taint walk); it runs exactly when the DP runner would pick the
        # shard_map path — same predicate, so the two can never drift
        from . import shard_analysis
        from ..parallel.data_parallel import _program_has_collectives

        tainted = (shard_analysis.variant_names(program, block)
                   if _program_has_collectives(program) else set())
        self._attrs = {OP_ROLE_KEY: int(OpRole.Optimize),
                       "op_namescope": "/numerics_probe/"}
        scalars: List[str] = []
        for i, t in enumerate(targets):
            scalars.extend(self._emit(block, t, i,
                                      combine=t["var"] in tainted))
        block.create_var(name=numerics.STATS_VAR,
                         shape=[len(scalars)], dtype=VarType.FP32)
        block.append_op("stack", inputs={"X": scalars},
                        outputs={"Y": [numerics.STATS_VAR]},
                        attrs=dict(self._attrs, axis=0))
        program._numerics_layout = targets
        program._bump_version()
        return program

    # -- emission ----------------------------------------------------------
    def _mk(self, block, name, shape, dtype):
        if not block.has_var(name):
            block.create_var(name=name, shape=list(shape), dtype=dtype)
        return name

    def _emit(self, block, t, idx, combine):
        """Probe ops for one target; returns the 5 scalar names in
        PARTIALS order (globally combined when ``combine``)."""
        var = t["var"]
        v = block._find_var_recursive(var)
        shape = list(v.shape) if v.shape else [-1]
        is_float = v.dtype in (VarType.FP16, VarType.BF16, VarType.FP32,
                               VarType.FP64)
        base = f"@nprobe@{idx}@"
        A = self._attrs
        f32 = self._mk(block, base + "f32", shape, VarType.FP32)
        block.append_op("cast", inputs={"X": [var]}, outputs={"Out": [f32]},
                        attrs=dict(A, out_dtype=int(VarType.FP32)))
        absv = self._mk(block, base + "abs", shape, VarType.FP32)
        block.append_op("abs", inputs={"X": [f32]},
                        outputs={"Out": [absv]}, attrs=dict(A))
        sq = self._mk(block, base + "sq", shape, VarType.FP32)
        block.append_op("square", inputs={"X": [f32]},
                        outputs={"Out": [sq]}, attrs=dict(A))
        # NON-finite mask, counted directly: summing a mask of zeros is
        # exact in f32 at ANY tensor size, where summing the finite
        # mask's ones loses integer precision past 2^24 elements and a
        # host-side `numel - finite` would report phantom nonfinites on
        # large healthy tensors.  isfinite runs on the raw value for
        # float vars (an f32 cast of f64 could overflow large-but-
        # finite values to inf), on the f32 copy for bool/int vars
        # (isfinite rejects bool inputs).
        finb = self._mk(block, base + "finb", shape, VarType.BOOL)
        block.append_op("isfinite_v2",
                        inputs={"X": [var if is_float else f32]},
                        outputs={"Out": [finb]}, attrs=dict(A))
        nfb = self._mk(block, base + "nfb", shape, VarType.BOOL)
        block.append_op("logical_not", inputs={"X": [finb]},
                        outputs={"Out": [nfb]}, attrs=dict(A))
        nff = self._mk(block, base + "nf", shape, VarType.FP32)
        block.append_op("cast", inputs={"X": [nfb]},
                        outputs={"Out": [nff]},
                        attrs=dict(A, out_dtype=int(VarType.FP32)))
        # numel via shape -> f32 -> reduce_prod (the `size` op would
        # request an int64 the x64-disabled runtime warns about)
        shp = self._mk(block, base + "shape", [len(shape)], VarType.INT32)
        block.append_op("shape", inputs={"Input": [var]},
                        outputs={"Out": [shp]}, attrs=dict(A))
        shpf = self._mk(block, base + "shapef", [len(shape)], VarType.FP32)
        block.append_op("cast", inputs={"X": [shp]},
                        outputs={"Out": [shpf]},
                        attrs=dict(A, out_dtype=int(VarType.FP32)))

        red = dict(A, dim=[0], keep_dim=False, reduce_all=True)
        out: List[str] = []
        for part, src, rop in (
                ("absmax", absv, "reduce_max"), ("sum", f32, "reduce_sum"),
                ("sumsq", sq, "reduce_sum"),
                ("nonfinite", nff, "reduce_sum")):
            local = self._mk(block, base + part, [], VarType.FP32)
            block.append_op(rop, inputs={"X": [src]},
                            outputs={"Out": [local]}, attrs=dict(red))
            out.append(local)
        numel = self._mk(block, base + "numel", [], VarType.FP32)
        block.append_op("reduce_prod", inputs={"X": [shpf]},
                        outputs={"Out": [numel]}, attrs=dict(red))
        out.append(numel)
        if combine:
            combined = []
            for part, local in zip(("absmax", "sum", "sumsq", "nonfinite",
                                    "numel"), out):
                g = self._mk(block, base + part + "_g", [], VarType.FP32)
                block.append_op(self._COMBINE[part], inputs={"X": [local]},
                                outputs={"Out": [g]},
                                attrs=dict(A, ring_id=0))
                combined.append(g)
            out = combined
        return out

@register_pass("shard_safety_pass")
class ShardSafetyPass(Pass):
    """Static SPMD shard-safety gate (framework/shard_analysis.py): runs
    the distribution-state abstract interpreter and its check catalog —
    replication soundness, collectives under divergent control flow,
    comm/compute hazards — over the compiled program.  Analysis-only:
    the program is returned untouched, findings land in ``self.report``
    and are warned (or raised under ``FLAGS_shard_safety_strict``) by
    the shared :func:`shard_analysis.gate`.  Appended LAST in the
    pipeline so it sees every pass's output, including the numerics
    probe's cross-shard stat contract."""

    feed_names: tuple = ()
    fetch_names: tuple = ()
    where: str = "shard_safety_pass"

    def apply(self, program):
        # Analysis-only: the program cannot be mutated, so the base
        # class's snapshot/verify bracket would only re-prove what the
        # pass never touches.  Skipping it keeps the gate's per-compile
        # cost at the cost of the analysis itself.
        out = self.apply_impl(program)
        return out if out is not None else program

    def apply_impl(self, program):
        from . import shard_analysis

        diags = shard_analysis.gate(
            program, feed_names=tuple(self.feed_names),
            fetch_names=tuple(self.fetch_names), where=self.where)
        self.report = {"diagnostics": [d.as_dict() for d in diags]}
        return program


@register_pass("fuse_optimizer_ops_pass")
class FuseOptimizerOpsPass(Pass):
    def apply_impl(self, program):
        fused = 0
        block = program.global_block()
        groups: Dict[tuple, List[Operator]] = {}
        for op_ in block.ops:
            if op_.type not in _FUSABLE_OPT:
                continue
            gname = op_.inputs.get("Grad", [None])[0]
            gvar = block._find_var_recursive(gname) if gname else None
            if gvar is not None and gvar.type == VarType.SELECTED_ROWS:
                continue  # sparse updates keep their per-param kernels
            attr_key = frozenset(
                (k, tuple(v) if isinstance(v, list) else v)
                for k, v in op_.attrs.items()
                if k not in ("op_role", "op_namescope", "op_callstack",
                             "op_role_var"))
            key = (op_.type, op_.inputs["LearningRate"][0], attr_key)
            groups.setdefault(key, []).append(op_)
        for (otype, lr, _), ops_ in groups.items():
            if len(ops_) < 2:
                continue
            in_slots, out_slots = _FUSABLE_OPT[otype]
            inputs = {"LearningRate": [lr]}
            outputs: Dict[str, List[str]] = {}
            for s in in_slots:
                inputs[s] = [o.inputs[s][0] for o in ops_]
            for s in out_slots:
                outputs[s] = [o.outputs[s][0] for o in ops_]
            attrs = dict(ops_[0].attrs)
            # insert where the LAST member was: every grad is produced by
            # then; nothing between reads the updated params (updates are
            # the program tail)
            last = max(block.ops.index(o) for o in ops_)
            last -= sum(1 for o in ops_ if block.ops.index(o) < last)
            remove_ops(block, ops_)
            block._insert_op(last, "fused_" + otype, inputs=inputs,
                             outputs=outputs, attrs=attrs)
            fused += 1
        self.fused_count = fused
        if fused:
            program._bump_version()
        return program


# --------------------------------------------------------------------------
# tensor-parallel serving decoder (inference/serving.py, FLAGS_serving_tp)
# --------------------------------------------------------------------------
@register_pass("serving_tp_pass")
class ServingTPPass(Pass):
    """Insert the Megatron combine collectives into a serving decoder
    SHARD program (one built with ``build_decoder_program(..., tp>1)``,
    whose head/width reshapes already bake the local sizes):

    * after the token+position embedding sum (``_srv_h0_*`` — both
      tables are hidden-sharded, so each rank holds ``1/tp`` of the
      columns): a ``c_concat`` (last-dim all-gather) reassembles the
      full residual width;
    * after each block's attention out-projection (``_srv_l{i}_o_*``)
      and MLP down-projection (``_srv_l{i}_ff2_*``) — the row-parallel
      matmuls whose outputs are partial sums: a ``c_allreduce_sum``;
    * around the tied-embedding logits head (``_srv_logits_*``): a
      ``c_split`` slices the full-width final hidden back to this
      rank's columns (matching ``dec_embed``'s shard), the matmul's
      partial logits then ``c_allreduce_sum`` to the full row.

    Consumers are rewired onto the combined values (pass-inserted
    producers are deliberate redirects under the verifier bracket).
    Every collective carries the serving TP ``ring_id`` so the
    lowering resolves the ``mp`` mesh axis, never the data-parallel
    ring.  ``inserted_count`` reports how many collectives landed —
    2 per block + 3 model-level for every program form."""

    ring_id: int = 0

    _H0 = re.compile(r"_srv_h0_\d+")
    _COMBINE = re.compile(r"_srv_l\d+_(?:o|ff2)_\d+")
    _LOGITS = re.compile(r"_srv_logits_\d+")

    def _redirect(self, block, start, old, new):
        for op_ in block.ops[start:]:
            op_.rename_input(old, new)

    def apply_impl(self, program):
        block = program.global_block()
        attrs = {"ring_id": int(self.ring_id)}
        inserted = 0
        i = 0
        while i < len(block.ops):
            op_ = block.ops[i]
            outs = [n for ns in op_.outputs.values() for n in ns]
            out = outs[0] if outs else None
            if op_.type == "elementwise_add" and out is not None \
                    and self._H0.fullmatch(out):
                full = block.create_var(name=out + "@TP_AG").name
                block._insert_op(i + 1, "c_concat",
                                 inputs={"X": [out]},
                                 outputs={"Out": [full]},
                                 attrs=dict(attrs))
                self._redirect(block, i + 2, out, full)
                inserted += 1
                i += 2
                continue
            if op_.type == "matmul" and out is not None \
                    and self._COMBINE.fullmatch(out):
                red = block.create_var(name=out + "@TP_AR").name
                block._insert_op(i + 1, "c_allreduce_sum",
                                 inputs={"X": [out]},
                                 outputs={"Out": [red]},
                                 attrs=dict(attrs))
                self._redirect(block, i + 2, out, red)
                inserted += 1
                i += 2
                continue
            if op_.type == "matmul" and out is not None \
                    and self._LOGITS.fullmatch(out):
                hf = op_.inputs["X"][0]
                loc = block.create_var(name=hf + "@TP_SPLIT").name
                block._insert_op(i, "c_split",
                                 inputs={"X": [hf]},
                                 outputs={"Out": [loc]},
                                 attrs=dict(attrs))
                op_.rename_input(hf, loc)
                red = block.create_var(name=out + "@TP_AR").name
                block._insert_op(i + 2, "c_allreduce_sum",
                                 inputs={"X": [out]},
                                 outputs={"Out": [red]},
                                 attrs=dict(attrs))
                self._redirect(block, i + 3, out, red)
                if getattr(program, "_srv_logits", None) == out:
                    program._srv_logits = red
                inserted += 2
                i += 3
                continue
            i += 1
        self.inserted_count = inserted
        if inserted:
            program._bump_version()
        return program


# ==========================================================================
# Plan-driven memory relief (rematerialization / host offload / plan
# escalation), priced per-var by the calibrated cost model
# ==========================================================================
_RELIEF_SCOPE = "/memory_relief/"
_RELIEF_MARK = "@RELIEF@"
_REMAT_SUFFIX = "@RELIEF@REMAT"
_D2H_SUFFIX = "@RELIEF@D2H"   # endswith @D2H => zero device bytes (planner)
_H2D_SUFFIX = "@RELIEF@H2D"


def _role_of(op_) -> int:
    try:
        return int(op_.attrs.get("op_role", 0))
    except Exception:
        return 0


def _read_in_subblocks(program: Program, name: str) -> bool:
    for blk in program.blocks:
        if blk.idx == 0:
            continue
        for op_ in blk.ops:
            if name in op_.input_arg_names:
                return True
    return False


def price_relief_candidates(program: Program, plan, cm, mode: str = "auto",
                            done=()) -> List[dict]:
    """Price remat / offload fixes for every activation whose lifetime
    crosses the modeled peak op, cheapest modeled seconds-per-byte-saved
    first.  ``plan`` is a ``MemoryPlan``; ``cm`` a ``CostModel``.  Only
    fixes that can actually lower *the* peak qualify: the var must be
    produced before and next consumed after ``plan.peak_op_index``."""
    from ..backward import OpRole
    from ..ops.registry import OPS
    from ..utils.cost_model import COMM_OPS, op_time_s
    from .verifier import EMPTY

    block = program.global_block()
    ops = list(block.ops)
    peak_i = plan.peak_op_index
    if peak_i is None:
        return []
    done = set(done)
    producer_at: Dict[str, int] = {}
    consumers: Dict[str, List[int]] = {}
    writers: Dict[str, List[int]] = {}
    for i, op_ in enumerate(ops):
        for nm in op_.input_arg_names:
            consumers.setdefault(nm, []).append(i)
        for nm in op_.output_arg_names:
            producer_at.setdefault(nm, i)
            writers.setdefault(nm, []).append(i)
    # per-op compute time; collectives ride the comm stream and hide
    # nothing for the host link
    op_s = [0.0 if op_.type in COMM_OPS else op_time_s(op_, block, cm)
            for op_ in ops]
    cum = [0.0]
    for s in op_s:
        cum.append(cum[-1] + s)  # cum[i] = compute time before op i

    bwd_bit = int(OpRole.Backward)
    out: List[dict] = []
    for name, info in (plan.per_var or {}).items():
        if info.get("class") != "activation" or info.get("resident"):
            continue
        if name in done or _RELIEF_MARK in name or name == EMPTY:
            continue
        saved = int(info.get("dev_bytes") or 0)
        if saved <= 0:
            continue
        p = producer_at.get(name)
        cons = consumers.get(name, [])
        bwd = [i for i in cons if _role_of(ops[i]) & bwd_bit]
        fwd = [i for i in cons if not (_role_of(ops[i]) & bwd_bit)]
        if p is None or not bwd:
            continue
        f_last = max(fwd) if fwd else p
        b_first = min(bwd)
        if not (f_last < peak_i < b_first):
            continue
        v = block._find_var_recursive(name)
        if v is None or v.shape is None:
            continue
        if _read_in_subblocks(block.program, name):
            continue  # sub-block capture: renaming would miss readers
        # ---- (a) rematerialize: replay the producer before b_first ----
        if mode in ("remat", "auto") and fwd:
            P = ops[p]
            d = OPS.get(P.type)
            real_outs = [o for o in P.output_arg_names if o != EMPTY]
            ok = (d is not None and not d.stateful and not d.host
                  and P.type not in COMM_OPS
                  and real_outs == [name]
                  and name not in P.input_arg_names
                  and not any(isinstance(a, Block)
                              for a in P.attrs.values()))
            if ok:
                # every producer input must still hold the same value
                # at the replay point
                for nm in set(P.input_arg_names):
                    if any(p < w < b_first for w in writers.get(nm, ())):
                        ok = False
                        break
            # replaying the producer revives its inputs: any input
            # that currently dies before the peak would be dragged back
            # across it, un-saving its own bytes — charge that against
            # the fix (single-op replay granularity: a chain remat that
            # nets zero is skipped, offload covers those vars instead)
            net = saved
            if ok:
                for nm in set(P.input_arg_names):
                    inm = (plan.per_var or {}).get(nm)
                    if inm is None or inm.get("resident"):
                        continue
                    last_use = max(consumers.get(nm, [p]) + [p])
                    if last_use < peak_i:
                        net -= int(inm.get("dev_bytes") or 0)
            if ok and net > 0:
                cost = max(op_s[p], cm.launch_s)
                out.append({"var": name, "fix": "remat",
                            "saved_bytes": net, "cost_s": cost,
                            "seconds_per_byte": cost / net,
                            "producer_index": p, "f_last": f_last,
                            "b_first": b_first})
        # ---- (b) host offload: d2h after f_last, h2d hoisted so the
        # transfer hides behind backward compute (r14 double-buffering) --
        if mode in ("offload", "auto"):
            d2h_s = saved / cm.d2h_bytes_per_s
            h2d_s = saved / cm.h2d_bytes_per_s
            hide_d2h = max(cum[peak_i] - cum[min(f_last + 1, len(ops))],
                           0.0)
            # hoist the h2d back from the consumer until the transfer
            # hides behind backward compute — but never at-or-before
            # the peak op, else the value is back on device at the
            # peak and the fix saves nothing
            h = b_first
            acc = 0.0
            while h - 1 > max(f_last + 1, peak_i) and acc < h2d_s:
                h -= 1
                acc += op_s[h]
            cost = (2.0 * cm.launch_s + max(0.0, d2h_s - hide_d2h)
                    + max(0.0, h2d_s - acc))
            out.append({"var": name, "fix": "offload",
                        "saved_bytes": saved, "cost_s": cost,
                        "seconds_per_byte": cost / saved,
                        "f_last": f_last, "b_first": b_first,
                        "h_insert": h})
    out.sort(key=lambda c: (c["seconds_per_byte"], c["var"], c["fix"]))
    return out


def relief_candidate_summary(program: Program, plan, top: int = 3,
                             feed_names: Sequence[str] = (),
                             fetch_names: Sequence[str] = ()) -> List[dict]:
    """Cheapest candidate fix per var, for the over-budget warning
    (actionable even with FLAGS_memory_relief=off)."""
    from ..utils.cost_model import default_cost_model

    block = program.global_block()
    cm = default_cost_model(list(block.ops), block)
    best: Dict[str, dict] = {}
    for c in price_relief_candidates(program, plan, cm, mode="auto"):
        best.setdefault(c["var"], c)  # already sorted cheapest-first
    return [{k: c[k] for k in ("var", "fix", "saved_bytes", "cost_s",
                               "seconds_per_byte")}
            for c in list(best.values())[:int(top)]]


@register_pass("memory_relief_pass")
class MemoryReliefPass(Pass):
    """Spend modeled recompute time or host-transfer time to buy back
    HBM when ``plan_memory()``'s modeled peak exceeds
    ``FLAGS_hbm_budget_mb`` (``FLAGS_memory_relief={off,remat,offload,
    auto}``; ``off`` leaves the pipeline byte-identical).

    Greedy loop: price every candidate fix (remat / offload / plan
    escalation), apply the cheapest by modeled seconds-per-byte-saved,
    re-run ``plan_memory()`` so savings compound, repeat until the peak
    fits.  Decisions land in ``self.report`` (attached to
    ``compiled._memory_plan.relief`` by ``plan_and_surface``):

    * **remat** — the producing op is replayed immediately before the
      first backward consumer (same op, same inputs: bit-identical) and
      backward readers are redirected to the ``@RELIEF@REMAT`` copy, so
      the original activation dies at its last forward consumer.
    * **offload** — a ``memcpy_d2h`` right after the last forward
      consumer stages the value to host (``@D2H`` names charge zero
      device bytes in the planner) and a ``memcpy_h2d`` hoisted far
      enough ahead of the backward consumer that the transfer hides
      behind backward compute (the r14 double-buffering rule; the
      resulting windows are validated by the r10
      ``check_prefetch_plan`` rule).
    * **plan** — when modeled cheaper, escalate the r16 parallel plan
      instead (raise the ZeRO stage / shrink the prefetch window); the
      caller picks the new ``stage``/``prefetch_depth`` out of the
      report.

    Raises ``MemoryBudgetError`` naming the residual gap when the peak
    still does not fit and ``FLAGS_hbm_budget_strict`` is set.
    """

    feed_names: Sequence[str] = ()
    fetch_names: Sequence[str] = ()
    ndev: int = 1
    stage = None            # None: FLAGS_dp_sharding
    use_shard_map = None
    prefetch_depth = None   # None: FLAGS_dp_prefetch_depth
    scope = None
    mode: str = "auto"
    budget = None           # bytes; None: FLAGS_hbm_budget_mb
    allow_escalate: bool = False
    max_fixes: int = 64
    report: Optional[dict] = None

    def apply_impl(self, program: Program) -> Program:
        from ..utils.cost_model import default_cost_model
        from ..utils.flags import flag
        from . import memory_plan as _mp

        block = program.global_block()
        budget = int(self.budget) if self.budget else _mp.budget_bytes()
        mode = str(self.mode or "auto")
        stage = self.stage
        if stage is None:
            stage = int(flag("dp_sharding") or 0)
        pf_depth = self.prefetch_depth
        if pf_depth is None:
            pf_depth = int(flag("dp_prefetch_depth") or 0)
        report = self.report = {
            "mode": mode, "engaged": False, "budget_bytes": int(budget),
            "peak_before_bytes": 0, "peak_after_bytes": 0, "fixes": [],
            "bytes_saved": 0, "modeled_overhead_s": 0.0,
            "stage": int(stage), "prefetch_depth": int(pf_depth),
            "offload_windows": [],
        }
        if not budget or mode == "off":
            return program

        def replan(st=None, pf=None):
            return _mp.plan_memory(
                program, feed_names=tuple(self.feed_names),
                fetch_names=tuple(self.fetch_names), ndev=int(self.ndev),
                stage=(stage if st is None else st),
                use_shard_map=self.use_shard_map,
                prefetch_depth=(pf_depth if pf is None else pf),
                scope=self.scope)

        plan = replan()
        report["peak_before_bytes"] = int(plan.peak_bytes)
        report["peak_after_bytes"] = int(plan.peak_bytes)
        if plan.peak_bytes <= budget:
            return program
        report["engaged"] = True
        cm = default_cost_model(list(block.ops), block)
        done: set = set()
        changed = False
        while (plan.peak_bytes > budget
               and len(report["fixes"]) < int(self.max_fixes)):
            cands = price_relief_candidates(program, plan, cm, mode=mode,
                                            done=done)
            cands += self._price_h2d_sinks(block, plan, cm)
            cands.sort(key=lambda c: c["seconds_per_byte"])
            best = cands[0] if cands else None
            if self.allow_escalate and mode == "auto":
                esc = self._price_escalation(program, plan, cm, replan,
                                             stage, pf_depth)
                if esc is not None and (
                        best is None
                        or esc["seconds_per_byte"]
                        < best["seconds_per_byte"]):
                    best = esc
            if best is None:
                break
            before = plan.peak_bytes
            if best["fix"] == "remat":
                self._apply_remat(block, best)
                done.add(best["var"])
            elif best["fix"] == "offload":
                self._apply_offload(block, best)
                done.add(best["var"])
            elif best["fix"] == "sink":
                op_ = block.ops.pop(best["op_index"])
                block.ops.insert(best["new_index"], op_)
                changed = True
            else:  # plan escalation
                stage = int(best["stage"])
                pf_depth = int(best["prefetch_depth"])
                report["stage"] = stage
                report["prefetch_depth"] = pf_depth
            plan = replan()
            fx = {"var": best["var"], "fix": best["fix"],
                  "saved_bytes": int(max(before - plan.peak_bytes, 0)),
                  "modeled_cost_s": float(best["cost_s"]),
                  "seconds_per_byte": float(best["seconds_per_byte"])}
            if best["fix"] == "plan":
                fx["stage"] = stage
                fx["prefetch_depth"] = pf_depth
            report["fixes"].append(fx)
            report["modeled_overhead_s"] = float(
                report["modeled_overhead_s"] + best["cost_s"])
            if best["fix"] != "plan":
                changed = True
        report["peak_after_bytes"] = int(plan.peak_bytes)
        report["bytes_saved"] = int(
            max(report["peak_before_bytes"] - plan.peak_bytes, 0))
        if changed:
            program._bump_version()
            self._check_offload_windows(block)
        if plan.peak_bytes > budget:
            gap_mb = (plan.peak_bytes - budget) / float(1 << 20)
            report["residual_gap_mb"] = round(gap_mb, 3)
            from ..utils.flags import flag as _flag
            if bool(_flag("hbm_budget_strict")):
                raise _mp.MemoryBudgetError(
                    f"[memory_relief] modeled HBM peak "
                    f"{plan.peak_bytes / float(1 << 20):.1f} MB still "
                    f"exceeds FLAGS_hbm_budget_mb="
                    f"{budget / float(1 << 20):.1f} MB after "
                    f"{len(report['fixes'])} relief fix(es): residual "
                    f"gap {gap_mb:.3f} MB (mode={mode}; raise the "
                    f"budget, enable more fix kinds, or shrink the "
                    f"model)")
        return program

    # -- fix application ---------------------------------------------------
    def _apply_remat(self, block: Block, cand: dict) -> None:
        from ..backward import OP_ROLE_KEY, OpRole

        name = cand["var"]
        b_first = cand["b_first"]
        P = block.ops[cand["producer_index"]]
        new = name + _REMAT_SUFFIX
        src = block._find_var_recursive(name)
        if not block.has_var(new):
            block.create_var(name=new, shape=list(src.shape),
                             dtype=src.dtype)
        outputs = {slot: [new if n == name else n for n in names]
                   for slot, names in P.outputs.items()}
        attrs = dict(P.attrs)
        attrs[OP_ROLE_KEY] = int(OpRole.Backward)
        attrs["op_namescope"] = _RELIEF_SCOPE
        block._insert_op(b_first, P.type,
                         inputs={k: list(v) for k, v in P.inputs.items()},
                         outputs=outputs, attrs=attrs)
        for op_ in block.ops[b_first + 1:]:
            op_.rename_input(name, new)

    def _apply_offload(self, block: Block, cand: dict) -> None:
        from ..backward import OP_ROLE_KEY, OpRole

        name = cand["var"]
        f_last, h = cand["f_last"], cand["h_insert"]
        src = block._find_var_recursive(name)
        d2h, h2d = name + _D2H_SUFFIX, name + _H2D_SUFFIX
        for nm in (d2h, h2d):
            if not block.has_var(nm):
                block.create_var(name=nm, shape=list(src.shape),
                                 dtype=src.dtype)
        role_fwd = _role_of(block.ops[f_last])
        block._insert_op(f_last + 1, "memcpy_d2h",
                         inputs={"X": [name]}, outputs={"Out": [d2h]},
                         attrs={OP_ROLE_KEY: int(role_fwd),
                                "op_namescope": _RELIEF_SCOPE})
        hi = h + 1  # shifted by the d2h insert
        block._insert_op(hi, "memcpy_h2d",
                         inputs={"X": [d2h]}, outputs={"Out": [h2d]},
                         attrs={OP_ROLE_KEY: int(OpRole.Backward),
                                "op_namescope": _RELIEF_SCOPE})
        for op_ in block.ops[hi + 1:]:
            op_.rename_input(name, h2d)

    # -- window tightening: an h2d staged for overlap can end up BEFORE
    # the (moved) peak as the greedy loop reshapes the timeline — sinking
    # it just past the peak trades exposed transfer time for peak bytes
    def _price_h2d_sinks(self, block, plan, cm):
        from ..utils.cost_model import COMM_OPS, op_time_s

        peak_i = plan.peak_op_index
        if peak_i is None:
            return []
        ops = list(block.ops)
        op_s = [0.0 if o.type in COMM_OPS else op_time_s(o, block, cm)
                for o in ops]
        cum = [0.0]
        for t in op_s:
            cum.append(cum[-1] + t)
        out = []
        for i, op_ in enumerate(ops):
            if op_.type != "memcpy_h2d" \
                    or op_.attrs.get("op_namescope") != _RELIEF_SCOPE \
                    or i >= peak_i:
                continue
            nm = (op_.outputs.get("Out") or [None])[0]
            cons = [j for j in range(i + 1, len(ops))
                    if nm in ops[j].input_arg_names]
            if not cons or min(cons) <= peak_i:
                continue  # value needed at/before the peak: cannot sink
            saved = int((plan.per_var or {}).get(nm, {}).get("dev_bytes")
                        or 0)
            if saved <= 0:
                continue
            fc = min(cons)
            src = (op_.inputs.get("X") or [None])[0]
            h2d_s = saved / cm.h2d_bytes_per_s
            exposed_old = max(0.0, h2d_s - (cum[fc] - cum[i + 1]))
            exposed_new = max(0.0, h2d_s - (cum[fc] - cum[peak_i + 1]))
            cost = max(exposed_new - exposed_old, 0.0) + cm.launch_s
            out.append({"var": nm, "fix": "sink", "saved_bytes": saved,
                        "cost_s": cost, "seconds_per_byte": cost / saved,
                        "op_index": i, "new_index": peak_i,
                        "first_consumer": fc, "src": src})
        return out

    # -- fix (c): escalate the r16 parallel plan ---------------------------
    def _price_escalation(self, program, plan, cm, replan, stage,
                          pf_depth):
        if int(self.ndev) <= 1:
            return None
        import dataclasses

        from ..parallel import plan_search as _ps

        base = _ps.ParallelPlan.from_flags()
        base = dataclasses.replace(base, stage=int(stage),
                                   prefetch_depth=int(pf_depth))
        usm = bool(self.use_shard_map)
        try:
            t0 = _ps.modeled_step_time(
                program, int(self.ndev), base, usm)["modeled_step_s"]
        except Exception:
            return None
        moves = []
        if int(stage) < 3:
            moves.append((int(stage) + 1, int(pf_depth)))
        elif int(pf_depth) > 0:
            moves.append((int(stage), 0))
        best = None
        for st, pf in moves:
            try:
                p2 = replan(st=st, pf=pf)
                t2 = _ps.modeled_step_time(
                    program, int(self.ndev),
                    dataclasses.replace(base, stage=st,
                                        prefetch_depth=pf),
                    usm)["modeled_step_s"]
            except Exception:
                continue
            saved = int(plan.peak_bytes - p2.peak_bytes)
            if saved <= 0:
                continue
            cost = max(float(t2 - t0), 0.0) + cm.launch_s
            cand = {"var": "<plan>", "fix": "plan", "saved_bytes": saved,
                    "cost_s": cost, "seconds_per_byte": cost / saved,
                    "stage": st, "prefetch_depth": pf}
            if best is None or (cand["seconds_per_byte"]
                                < best["seconds_per_byte"]):
                best = cand
        return best

    # -- offload windows must satisfy the r10 prefetch-window rule ---------
    def _check_offload_windows(self, block: Block) -> None:
        from . import verifier

        ops = list(block.ops)
        records = []
        for i, op_ in enumerate(ops):
            if op_.type != "memcpy_h2d" \
                    or op_.attrs.get("op_namescope") != _RELIEF_SCOPE:
                continue
            out = (op_.outputs.get("Out") or [None])[0]
            cons = [j for j in range(i + 1, len(ops))
                    if out in ops[j].input_arg_names]
            if not cons:
                continue
            records.append({"param": out, "gather_at": i + 1,
                            "first_consumer": min(cons),
                            "last_consumer": max(cons)})
        self.report["offload_windows"] = records
        if records and verifier.enabled():
            verifier.check_prefetch_plan_or_raise(
                ops, block, records, "memory_relief_offload")
