"""Static program verifier: dataflow / alias hazard detection for the IR
pass pipeline.

Reference intent: the C++ stack validates its graph invariants at every
rewrite — OpDesc::CheckAttrs + OpProto slot declarations
(framework/op_desc.cc, op_proto_maker.cc), the pattern detector's
IsIntermediate safety rule (ir/graph_pattern_detector.cc) and the SSA
graph checks in ir/graph_helper.cc.  Our reproduction grew five
op-motion-heavy passes (fusion, NHWC layout, overlap anchor placement,
autotune bucketing, ZeRO-3 prefetch hoisting), each defending correctness
with its own local argument plus a bit-identity test.  This module is the
ONE analyzer that proves any transformed program hazard-free instead of N
local proofs ("End-to-end Adaptive Distributed Training on PaddlePaddle",
arXiv:2112.02752, leans on exactly this kind of static graph checking to
keep pass pipelines composable).

Three layers of checks:

* **dataflow** — per-op read/write sets (registry OpDef metadata;
  stateful/in-place ops write their inputs: output name == input name,
  see ir.py DeadCodeEliminationPass).  Absolute checks: possibly-
  uninitialized reads, orphaned (never-produced, never-declared) names,
  dead writes, sub-block capture visibility.  Pass-relative checks
  (``snapshot`` before / ``verify_pass`` after): RAW/WAR/WAW hazards
  introduced by op motion, found by *observed-writer correspondence* —
  an op carried across the pass must keep reading the value of the same
  producer (or a producer the pass itself inserted; a pass redirecting a
  survivor to a DIFFERENT surviving producer is exactly "moved an op past
  its anchor").
* **registry conformance** — unregistered op types; input/output slot
  names the op's lowering never consumes; required input slots missing;
  attr values whose type disagrees with the lowering's declared/default
  attrs.  Slot/attr declarations are DERIVED from the lowering itself by
  AST analysis (``ctx.in_/ins/has_input``, ``ctx.set_out/out_names``,
  ``ctx.attr(name, default)``), transitively through helper calls —
  the registry's one source of truth stays the code; ``op(...,
  spec_hint=...)`` supplements ops with dynamic slot access.
* **pipeline postconditions** — pluggable rules: NHWC passes leave no
  mixed-layout consumer; collective ops appear in identical order on
  every device's program (ring-deadlock check); ZeRO-3 prefetch gather
  windows never cross a write to their param; sub-block ops only capture
  vars visible in an ancestor block.

``FLAGS_verify_passes`` (default: on under pytest) arms the gate inside
``Pass.apply``: snapshot before, verify after, raise ``VerifyError``
naming the pass, the op index and the hazard.  ``tools/progcheck.py`` is
the standalone lint CLI over constructed/saved programs.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .core import Block, Operator, Program
from .dtype import VarType

EMPTY = "@EMPTY@"

SEV_ERROR = "error"
SEV_WARNING = "warning"

#: attrs the framework stamps on every op (roles, callstacks, device
#: annotations, grad-replay bookkeeping) — never op-declared
FRAMEWORK_ATTRS = frozenset({
    "op_role", "op_role_var", "op_namescope", "op_callstack", "op_device",
    "is_test", "use_mkldnn", "use_cudnn", "use_quantizer",
    "mkldnn_data_type", "with_quant_attr", "trainable_statistics",
    "sub_block", "block", "blocks", "skip_update",
})


class Diagnostic:
    """One finding.  ``key()`` is the structural identity used to tell a
    pass-INTRODUCED problem from a pre-existing one (op indices shift
    across a rewrite, so the key is positional only as a last resort)."""

    __slots__ = ("severity", "code", "message", "block_idx", "op_index",
                 "op_type", "var", "pass_name")

    def __init__(self, severity, code, message, block_idx=0, op_index=None,
                 op_type=None, var=None, pass_name=None):
        self.severity = severity
        self.code = code
        self.message = message
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var = var
        self.pass_name = pass_name

    #: codes whose identity is per-op (slot/attr conformance); dataflow
    #: findings key on the VAR alone — a pass that merely retypes the
    #: op touching a var (fusion) must not re-key a pre-existing finding
    _PER_OP_CODES = frozenset({
        "unknown-input-slot", "unknown-output-slot",
        "missing-required-input", "unknown-attr", "attr-type-mismatch",
        "unregistered-op",
    })

    def key(self):
        if self.code in self._PER_OP_CODES:
            return (self.code, self.block_idx, self.op_type, self.var)
        return (self.code, self.block_idx, self.var)

    def format(self) -> str:
        where = f"block {self.block_idx}"
        if self.op_index is not None:
            where += f" op #{self.op_index}"
        if self.op_type:
            where += f" ({self.op_type})"
        head = self.severity.upper()
        if self.pass_name:
            head += f" [{self.pass_name}]"
        return f"{head} {self.code}: {where}: {self.message}"

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return f"<Diagnostic {self.format()}>"


class VerifyError(RuntimeError):
    """Raised by the pass gate on error-severity findings."""

    def __init__(self, diagnostics: Sequence[Diagnostic], pass_name=None):
        self.diagnostics = list(diagnostics)
        self.pass_name = pass_name
        lines = [d.format() for d in self.diagnostics]
        head = (f"IR pass {pass_name!r} broke program invariants"
                if pass_name else "program verification failed")
        super().__init__(head + ":\n  " + "\n  ".join(lines))


def enabled() -> bool:
    from ..utils.flags import flag

    return bool(flag("verify_passes"))


# --------------------------------------------------------------------------
# OpSpec: slot/attr declarations derived from the lowering by AST scan
# --------------------------------------------------------------------------
class OpSpec:
    __slots__ = ("type", "in_slots", "out_slots", "required_in", "attrs",
                 "open_slots", "open_attrs", "_opt_in", "_delegates")

    def __init__(self, type):
        self.type = type
        self.in_slots: set = set()
        self.out_slots: set = set()
        self.required_in: set = set()  # in_/ins accesses with no guard
        self._opt_in: set = set()      # has_input / missing_ok accesses
        self.attrs: Dict[str, Any] = {}   # name -> default (None = unknown)
        self.open_slots = False  # dynamic slot access seen: skip slot checks
        self.open_attrs = False  # dynamic attr access seen: skip attr checks
        self._delegates: set = set()  # OPS["x"].lower(ctx) alias targets


_IN_METHODS = {"in_", "ins", "has_input"}
_OUT_METHODS = {"set_out", "out_names", "has_output"}
_OPTIONAL_IN = {"has_input"}

_spec_cache: Dict[str, Optional[OpSpec]] = {}


def _literal(node):
    try:
        return True, ast.literal_eval(node)
    except Exception:
        return False, None


def _scan_callable(fn, spec: OpSpec, seen: set, depth: int):
    """Collect ctx-method usages from ``fn``'s source, following helper
    calls resolvable through globals/closure/default args (the `_unary`
    / `_ew` factory idiom keeps the real slot reads one level down)."""
    if depth > 4 or not callable(fn) or id(fn) in seen:
        return
    seen.add(id(fn))
    try:
        code = fn.__code__
    except AttributeError:
        return
    if "paddle_tpu" not in (code.co_filename or ""):
        return
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except Exception:
        spec.open_slots = spec.open_attrs = True
        return

    callees: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in ("op", "env"):
            # direct ctx.op.inputs / ctx.env access: the lowering reads
            # arbitrary slots — declarations can't be derived
            spec.open_slots = True
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            callees.append(f.id)
            if f.id == "getattr":
                spec.open_slots = spec.open_attrs = True
            continue
        if not isinstance(f, ast.Attribute):
            continue
        meth = f.attr
        if meth == "lower" and isinstance(f.value, ast.Subscript) and \
                isinstance(f.value.value, ast.Name) and \
                f.value.value.id == "OPS":
            # the alias idiom: OPS["batch_norm"].lower(ctx) — inherit
            # the target op's derived spec
            ok, target = _literal(f.value.slice)
            if ok and isinstance(target, str):
                spec._delegates.add(target)
            else:
                spec.open_slots = spec.open_attrs = True
            continue
        if meth in _IN_METHODS or meth in _OUT_METHODS or meth == "attr":
            if not node.args:
                continue
            ok, name = _literal(node.args[0])
            if not ok or not isinstance(name, str):
                if meth == "attr":
                    spec.open_attrs = True
                else:
                    spec.open_slots = True
                continue
            if meth in _IN_METHODS:
                spec.in_slots.add(name)
                missing_ok = any(kw.arg == "missing_ok"
                                 for kw in node.keywords) or (
                    len(node.args) > 1 and _literal(node.args[1])[1])
                if meth in _OPTIONAL_IN or missing_ok:
                    spec._opt_in.add(name)
                else:
                    spec.required_in.add(name)
            elif meth in _OUT_METHODS:
                spec.out_slots.add(name)
            else:  # attr
                default = None
                if len(node.args) > 1:
                    ok, default = _literal(node.args[1])
                    if not ok:
                        default = None
                for kw in node.keywords:
                    if kw.arg == "default":
                        ok, default = _literal(kw.value)
                        if not ok:
                            default = None
                if name not in spec.attrs or spec.attrs[name] is None:
                    spec.attrs[name] = default

    # resolve helper callees: globals, closure cells, callable defaults
    env: Dict[str, Any] = {}
    env.update(getattr(fn, "__globals__", {}) or {})
    freevars = code.co_freevars
    closure = getattr(fn, "__closure__", None) or ()
    for name, cell in zip(freevars, closure):
        try:
            env[name] = cell.cell_contents
        except ValueError:
            pass
    for name in callees:
        target = env.get(name)
        if target is not None and inspect.isfunction(target):
            _scan_callable(target, spec, seen, depth + 1)
    for d in (getattr(fn, "__defaults__", None) or ()):
        if inspect.isfunction(d):
            _scan_callable(d, spec, seen, depth + 1)
    kwd = getattr(fn, "__kwdefaults__", None) or {}
    for d in kwd.values():
        if inspect.isfunction(d):
            _scan_callable(d, spec, seen, depth + 1)


def op_spec(op_type: str) -> Optional[OpSpec]:
    """Derived (and cached) slot/attr declarations for ``op_type``;
    None when the op is unregistered or has no scannable lowering."""
    if op_type in _spec_cache:
        return _spec_cache[op_type]
    from ..ops import registry

    d = registry.OPS.get(op_type)
    spec: Optional[OpSpec] = None
    if d is not None and d.lower is not None:
        spec = OpSpec(op_type)
        _spec_cache[op_type] = spec  # break delegation cycles
        _scan_callable(d.lower, spec, set(), 0)
        for target in sorted(spec._delegates):
            if target == op_type:
                continue
            tspec = op_spec(target)
            if tspec is None:
                continue
            spec.in_slots.update(tspec.in_slots)
            spec.out_slots.update(tspec.out_slots)
            spec.required_in.update(tspec.required_in)
            spec._opt_in.update(tspec._opt_in)
            for k, v in tspec.attrs.items():
                if spec.attrs.get(k) is None:
                    spec.attrs[k] = v
            spec.open_slots |= tspec.open_slots
            spec.open_attrs |= tspec.open_attrs
        spec.required_in -= spec._opt_in
        hint = getattr(d, "spec_hint", None)
        if hint:
            spec.in_slots.update(hint.get("inputs", ()))
            spec.out_slots.update(hint.get("outputs", ()))
            for k, v in (hint.get("attrs", None) or {}).items():
                spec.attrs.setdefault(k, v)
            for s in hint.get("optional_inputs", ()):
                spec.in_slots.add(s)
                spec.required_in.discard(s)
            if hint.get("open"):
                spec.open_slots = spec.open_attrs = True
        if d.infer_shape is not None:
            # a custom InferShape may read slots/attrs the lowering
            # doesn't (e.g. shape-carrying attrs) — fold it in
            _scan_callable(d.infer_shape, spec, set(), 0)
            spec.required_in.clear()  # infer fns read op.inputs directly
            spec.open_slots = True
    _spec_cache[op_type] = spec
    return spec


def _is_grad_type(op_type: str) -> bool:
    return op_type.endswith("_grad")


def _attr_type_ok(value, default) -> bool:
    """Loose conformance: flag only clear disagreements.  int<->float
    interchange, bool-as-int, VarType-as-int, scalar-vs-0d are all fine;
    str-vs-number and list-vs-scalar are not."""
    if default is None or value is None:
        return True
    if isinstance(default, bool):
        return not isinstance(value, str) and not isinstance(value, (list, tuple))
    if isinstance(default, (int, float)):
        try:
            import numpy as np

            if isinstance(value, (bool, int, float, np.integer, np.floating,
                                  VarType)):
                return True
        except Exception:
            pass
        return not isinstance(value, (str, list, tuple, dict))
    if isinstance(default, str):
        return isinstance(value, str)
    if isinstance(default, (list, tuple)):
        try:
            import numpy as np

            return isinstance(value, (list, tuple, np.ndarray))
        except Exception:
            return isinstance(value, (list, tuple))
    return True


# --------------------------------------------------------------------------
# read/write event model
# --------------------------------------------------------------------------
def op_reads_writes(op_: Operator) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """The op's (reads, writes).  In-place/stateful ops write their
    inputs via output name == input name, so declared outputs already
    carry the in-place write set."""
    reads = tuple(n for n in op_.input_arg_names if n != EMPTY)
    writes = tuple(n for n in op_.output_arg_names if n != EMPTY)
    return reads, writes


def block_events(block: Block) -> List[Tuple[Operator, tuple, tuple]]:
    return [(op_,) + op_reads_writes(op_) for op_ in block.ops]


def _sub_block_attrs(op_: Operator) -> List[Block]:
    out = []
    for k, v in op_.attrs.items():
        if isinstance(v, Block):
            out.append(v)
        elif isinstance(v, int) and k.endswith("block"):
            try:
                out.append(op_.block.program.blocks[v])
            except Exception:
                pass
    return out


def _is_loop_block(program: Program, block: Block) -> bool:
    """Blocks owned by while-style ops carry loop-carried reads (read at
    the top, written at the bottom) — use-before-def does not apply."""
    for blk in program.blocks:
        for op_ in blk.ops:
            if op_.type in ("while", "while_loop", "recurrent"):
                if any(b is block for b in _sub_block_attrs(op_)):
                    return True
    return False


# --------------------------------------------------------------------------
# absolute checks (no snapshot needed)
# --------------------------------------------------------------------------
def check_registry(program: Program) -> List[Diagnostic]:
    from ..ops import registry

    diags: List[Diagnostic] = []
    for blk in program.blocks:
        for i, op_ in enumerate(blk.ops):
            t = op_.type
            d = registry.OPS.get(t)
            if _is_grad_type(t):
                if d is None or d.lower is None \
                        or getattr(d, "_generic_grad", False):
                    fwd = registry.OPS.get(t[: -len("_grad")])
                    if fwd is not None and fwd.lower is not None:
                        continue  # generic vjp grad materializes lazily
                    diags.append(Diagnostic(
                        SEV_ERROR, "unregistered-op",
                        f"grad op type {t!r} has no lowering and no "
                        f"forward op to derive a generic grad from",
                        blk.idx, i, t))
                    continue
                # custom grad lowering: falls through to slot checks
            elif d is None or d.lower is None:
                # a grad-maker-/infer-only OpDef is as unexecutable as
                # an unknown type — the executor would fail mid-trace
                detail = ("is not in the registry" if d is None
                          else "is registered without a lowering")
                diags.append(Diagnostic(
                    SEV_ERROR, "unregistered-op",
                    f"op type {t!r} {detail}", blk.idx, i, t))
                continue
            spec = op_spec(t)
            if spec is None:
                continue
            is_grad = _is_grad_type(t)
            if not spec.open_slots:
                for slot in op_.inputs:
                    if slot not in spec.in_slots:
                        diags.append(Diagnostic(
                            SEV_WARNING, "unknown-input-slot",
                            f"input slot {slot!r} is never consumed by the "
                            f"{t!r} lowering", blk.idx, i, t, var=slot))
                for slot in op_.outputs:
                    if slot not in spec.out_slots:
                        diags.append(Diagnostic(
                            SEV_WARNING, "unknown-output-slot",
                            f"output slot {slot!r} is never produced by the "
                            f"{t!r} lowering", blk.idx, i, t, var=slot))
                for slot in spec.required_in:
                    names = op_.inputs.get(slot, [])
                    if not names or all(n == EMPTY for n in names):
                        diags.append(Diagnostic(
                            SEV_WARNING, "missing-required-input",
                            f"required input slot {slot!r} of {t!r} is "
                            f"missing/empty", blk.idx, i, t, var=slot))
            if not spec.open_attrs and not is_grad:
                # grad ops carry a full fwd-attr snapshot by design —
                # attr conformance applies to forward ops only
                for name, value in op_.attrs.items():
                    if name.startswith("__") or name in FRAMEWORK_ATTRS:
                        continue
                    if name not in spec.attrs:
                        diags.append(Diagnostic(
                            SEV_WARNING, "unknown-attr",
                            f"attr {name!r} is never read by the {t!r} "
                            f"lowering (undeclared)", blk.idx, i, t,
                            var=name))
                    elif not _attr_type_ok(value, spec.attrs[name]):
                        diags.append(Diagnostic(
                            SEV_ERROR, "attr-type-mismatch",
                            f"attr {name!r} = {value!r} "
                            f"({type(value).__name__}) disagrees with the "
                            f"{t!r} lowering's default "
                            f"{spec.attrs[name]!r}", blk.idx, i, t,
                            var=name))
    return diags


def _visible_names(program: Program, block: Block) -> Tuple[set, set]:
    """(declared, written) name sets visible from ``block``: its own and
    every ancestor's var declarations and op writes."""
    declared: set = set()
    written: set = set()
    blk: Optional[Block] = block
    guard = 0
    while blk is not None and guard < 64:
        declared.update(blk.vars)
        for op_ in blk.ops:
            written.update(n for n in op_.output_arg_names if n != EMPTY)
        blk = blk.parent_block
        guard += 1
    return declared, written


def check_dataflow(program: Program, feed_names=(),
                   fetch_names=()) -> List[Diagnostic]:
    """Use-before-def / orphaned reads / dead writes / capture
    visibility.  Severities are conservative (see module docstring): the
    executor tolerates scope-resident values the program never writes,
    so absolute findings are warnings except capture violations; the
    pass gate upgrades NEW findings to errors."""
    diags: List[Diagnostic] = []
    feed_names = set(feed_names)
    all_declared = {n for b in program.blocks for n in b.vars}
    for blk in program.blocks:
        declared, written_visible = _visible_names(program, blk)
        parent = blk.parent_block
        ancestor_written = (_visible_names(program, parent)[1]
                            if parent is not None else set())
        is_loop = blk.idx != 0 and _is_loop_block(program, blk)
        events = block_events(blk)
        written_before: set = set()
        writes_all = set()
        for _, _, ws in events:
            writes_all.update(ws)
        # sub-block free reads count as reads of the parent value
        sub_reads: Dict[int, set] = {}
        for i, (op_, _, _) in enumerate(events):
            free: set = set()
            for sb in _sub_block_attrs(op_):
                for sop in sb.ops:
                    free.update(n for n in sop.input_arg_names
                                if n != EMPTY and n not in sb.vars)
            if free:
                sub_reads[i] = free
        last_read: Dict[str, int] = {}
        for i, (op_, rs, ws) in enumerate(events):
            for n in set(rs) | sub_reads.get(i, set()):
                last_read[n] = i
        for i, (op_, rs, ws) in enumerate(events):
            for n in set(rs):
                if n.startswith("@"):
                    continue
                if n in ws:
                    # in-place read+write (allreduce, optimizer update):
                    # an unwritten-before read observes the scope value
                    # legitimately — state, not use-before-def.  The
                    # name must still resolve somewhere, though: a
                    # rename that misses an in-place op (out == in)
                    # leaves it reading stale scope state.  The op's own
                    # write pollutes written_visible, so test declared /
                    # ancestor writes instead.
                    if n not in declared and n not in feed_names \
                            and n not in written_before \
                            and n not in ancestor_written:
                        if blk.idx != 0 and n in all_declared:
                            diags.append(Diagnostic(
                                SEV_ERROR, "subblock-capture",
                                f"op reads {n!r} in place, which is "
                                f"declared only in a non-ancestor block "
                                f"— sub-block ops may only capture vars "
                                f"visible in an ancestor",
                                blk.idx, i, op_.type, var=n))
                        else:
                            diags.append(Diagnostic(
                                SEV_WARNING, "orphaned-read",
                                f"op reads and writes {n!r} in place, "
                                f"but no visible block declares it "
                                f"(orphaned name — stale after a "
                                f"rename?)", blk.idx, i, op_.type,
                                var=n))
                    written_before.add(n)
                    continue
                v = blk._find_var_recursive(n)
                persist = v is not None and (getattr(v, "persistable", False)
                                             or getattr(v, "is_data", False))
                if n in written_before or n in feed_names or persist:
                    continue
                if n not in declared and n not in written_visible:
                    sev = SEV_WARNING
                    code = ("subblock-capture" if blk.idx != 0
                            and n in all_declared else "orphaned-read")
                    if code == "subblock-capture":
                        sev = SEV_ERROR
                        msg = (f"op reads {n!r}, which is declared only in "
                               f"a non-ancestor block — sub-block ops may "
                               f"only capture vars visible in an ancestor")
                    else:
                        msg = (f"op reads {n!r}, which no visible block "
                               f"declares and no visible op writes "
                               f"(orphaned name — stale after a rename?)")
                    diags.append(Diagnostic(sev, code, msg, blk.idx, i,
                                            op_.type, var=n))
                elif n in writes_all and not is_loop:
                    diags.append(Diagnostic(
                        SEV_WARNING, "use-before-def",
                        f"op reads {n!r} before the op that writes it "
                        f"(value must come from the scope)", blk.idx, i,
                        op_.type, var=n))
            for n in ws:
                written_before.add(n)
        # dead writes: nothing (op, sub-block or fetch-side persistable)
        # reads the value after its last write
        if blk.idx == 0:
            last_write: Dict[str, int] = {}
            for i, (op_, rs, ws) in enumerate(events):
                for n in ws:
                    last_write[n] = i
            for n, i in last_write.items():
                if n.startswith("@") or last_read.get(n, -1) >= i \
                        or n in fetch_names:
                    continue
                op_, rs, _ = events[i]
                if n in rs:
                    continue  # in-place update: the write IS the effect
                v = blk._find_var_recursive(n)
                if v is not None and getattr(v, "persistable", False):
                    continue
                diags.append(Diagnostic(
                    SEV_WARNING, "dead-write",
                    f"op writes {n!r} but nothing reads it afterwards",
                    blk.idx, i, op_.type, var=n))
    return diags


# --------------------------------------------------------------------------
# NHWC layout postcondition
# --------------------------------------------------------------------------
def check_nhwc(program: Program) -> List[Diagnostic]:
    """After layout_transform_pass no consumer may mix layouts: an
    NHWC-mode op must not read a value a sensitive op produced in NCHW
    (and vice versa), and only the pass's own boundary transposes may
    consume its ``@NHWC`` alias vars from generic ops."""
    from .ir import _LAYOUT_AGNOSTIC, _LAYOUT_OPS, _NHWC_SUFFIX

    diags: List[Diagnostic] = []
    for blk in program.blocks:
        label: Dict[str, str] = {}  # var -> "NHWC" | "NCHW"

        def produced(names, lay):
            for n in names:
                if n == EMPTY:
                    continue
                if lay is None:
                    label.pop(n, None)
                else:
                    label[n] = lay

        for i, op_ in enumerate(blk.ops):
            t = op_.type
            if t in ("transpose2", "transpose"):
                axis = list(op_.attrs.get("axis", ()))
                outs = op_.outputs.get("Out", [])
                if axis == [0, 2, 3, 1]:
                    produced(outs, "NHWC")
                elif axis == [0, 3, 1, 2]:
                    produced(outs, "NCHW")
                else:
                    produced(outs, None)
                continue
            spec = _LAYOUT_OPS.get(t)
            if spec is not None:
                attr_name, din, dout = spec
                mode = op_.attrs.get(attr_name, "NCHW")
                for slot in din:
                    for n in op_.inputs.get(slot, []):
                        lay = label.get(n)
                        if lay is None:
                            continue
                        if mode == "NHWC" and lay == "NCHW":
                            diags.append(Diagnostic(
                                SEV_ERROR, "mixed-layout-consumer",
                                f"NHWC-mode {t!r} reads {n!r}, which was "
                                f"produced in NCHW", blk.idx, i, t, var=n))
                        elif mode != "NHWC" and lay == "NHWC":
                            diags.append(Diagnostic(
                                SEV_ERROR, "mixed-layout-consumer",
                                f"{mode}-mode {t!r} reads {n!r}, which was "
                                f"produced in NHWC", blk.idx, i, t, var=n))
                for slot in dout:
                    produced(op_.outputs.get(slot, []),
                             "NHWC" if mode == "NHWC" else "NCHW")
                continue
            agn = _LAYOUT_AGNOSTIC.get(t)
            if agn is not None:
                din, dout = agn
                lays = set()
                for slot in din:
                    for n in op_.inputs.get(slot, []):
                        if n != EMPTY and n in label:
                            lays.add(label[n])
                if lays == {"NHWC", "NCHW"}:
                    diags.append(Diagnostic(
                        SEV_ERROR, "mixed-layout-consumer",
                        f"layout-agnostic {t!r} mixes NHWC and NCHW data "
                        f"inputs", blk.idx, i, t))
                out_lay = "NHWC" if lays == {"NHWC"} else (
                    "NCHW" if lays == {"NCHW"} else None)
                for slot in dout:
                    produced(op_.outputs.get(slot, []), out_lay)
                continue
            # generic op: consuming a pass-created @NHWC alias here means
            # the pass failed to materialize the NCHW value first
            for n in op_.input_arg_names:
                if n.endswith(_NHWC_SUFFIX) and label.get(n) == "NHWC":
                    diags.append(Diagnostic(
                        SEV_ERROR, "mixed-layout-consumer",
                        f"generic op {t!r} reads NHWC alias {n!r} (expects "
                        f"NCHW data)", blk.idx, i, t, var=n))
            for names in op_.outputs.values():
                produced(names, None)
    return diags


# --------------------------------------------------------------------------
# pluggable cross-program / plan rules
# --------------------------------------------------------------------------
_LOCAL_SYNC_OPS = frozenset({
    "c_sync_calc_stream", "c_sync_comm_stream", "c_wait_comm_stream",
    "c_wait_calc_stream", "c_gen_nccl_id", "c_comm_init",
    "c_comm_init_all", "gen_nccl_id",
})


#: reduce operation implied by the collective op TYPE (the lowering
#: dispatches on the type, not an attr — see ops/collective_ops.py)
_REDUCE_OF_TYPE = {
    "c_allreduce_sum": "sum", "c_allreduce_max": "max",
    "c_allreduce_min": "min", "c_allreduce_prod": "prod",
    "c_fused_allreduce": "sum", "allreduce": "sum",
    "c_reducescatter": "sum", "c_fused_reduce_scatter": "sum",
    "c_reduce_sum": "sum", "c_reduce_max": "max", "c_reduce_min": "min",
}


def _signature_walk(blk, sig, visited):
    from .dtype import dtype_name

    if id(blk) in visited:
        return
    visited.add(id(blk))
    for op_ in blk.ops:
        t = op_.type
        # a collective inside a sub-block (while body, cond branch)
        # executes AT the parent op's position — descend in place so the
        # fingerprint reflects issue order, the property NCCL rings care
        # about (a while lowers to scan: its body's collectives repeat
        # here, identically on every device or not at all)
        for sub in _sub_block_attrs(op_):
            _signature_walk(sub, sig, visited)
        if not (t.startswith("c_") or t in ("allreduce", "broadcast",
                                            "barrier")):
            continue
        if t in _LOCAL_SYNC_OPS:
            continue
        shape = dt = None
        names = op_.inputs.get("X", []) or op_.input_arg_names
        if names:
            v = blk._find_var_recursive(names[0])
            if v is not None and v.shape is not None:
                shape = tuple(v.shape)
            if v is not None and v.dtype is not None:
                try:
                    dt = dtype_name(v.dtype)
                except ValueError:
                    dt = str(v.dtype)
        sig.append((t, op_.attrs.get("ring_id", 0), len(names), shape,
                    _REDUCE_OF_TYPE.get(t), dt))
    return


def collective_signature(program: Program) -> List[tuple]:
    """Ordered (type, ring_id, nargs, payload shape, reduce-op, dtype)
    of every order-sensitive collective — the ring-deadlock fingerprint:
    two devices whose sequences diverge will block each other forever,
    and (r26) a reduce-op/dtype divergence on the SAME slot corrupts
    data silently instead, so both ride one signature.  Sub-blocks are
    visited at their parent op's position (issue order), then any block
    unreachable from block 0 is swept for coverage."""
    sig: List[tuple] = []
    visited: set = set()
    if program.blocks:
        _signature_walk(program.blocks[0], sig, visited)
    for blk in program.blocks:
        _signature_walk(blk, sig, visited)
    return sig


def check_collective_order(programs: Sequence[Program]) -> List[Diagnostic]:
    """Every device must issue the same collectives in the same order
    (reference: the NCCL ring-deadlock invariant multi_devices_graph_pass
    maintains by construction)."""
    diags: List[Diagnostic] = []
    if len(programs) < 2:
        return diags
    base = collective_signature(programs[0])
    for r, prog in enumerate(programs[1:], start=1):
        sig = collective_signature(prog)
        n = min(len(base), len(sig))
        for i in range(n):
            if base[i] != sig[i]:
                diags.append(Diagnostic(
                    SEV_ERROR, "collective-order-mismatch",
                    f"device 0 issues {base[i]} as collective #{i} but "
                    f"device {r} issues {sig[i]} — ring deadlock",
                    op_index=i, op_type=sig[i][0]))
                break
        else:
            if len(base) != len(sig):
                diags.append(Diagnostic(
                    SEV_ERROR, "collective-order-mismatch",
                    f"device 0 issues {len(base)} collectives but device "
                    f"{r} issues {len(sig)} — ring deadlock",
                    op_index=n))
    return diags


def check_prefetch_plan(ops: Sequence[Operator], block: Block,
                        records: Sequence[dict]) -> List[Diagnostic]:
    """ZeRO-3 prefetch windows (data_parallel._plan_param_prefetch) must
    never span a write to their parameter: a consumer after the write
    would read the stale gathered copy.  Generalizes the planner's local
    never-hoist-past-a-write rule to the whole window."""
    diags: List[Diagnostic] = []
    for rec in records:
        p = rec.get("param")
        lo = int(rec.get("gather_at", 0))
        hi = int(rec.get("last_consumer", lo))
        first = int(rec.get("first_consumer", hi))
        if not (lo <= first <= hi):
            diags.append(Diagnostic(
                SEV_ERROR, "prefetch-window-invalid",
                f"prefetch window for {p!r} is inverted: gather_at={lo}, "
                f"first_consumer={first}, last_consumer={hi}",
                op_index=lo, var=p, pass_name="dp_prefetch_plan"))
            continue
        for i in range(lo, min(hi + 1, len(ops))):
            op_ = ops[i]
            if p in op_.output_arg_names:
                diags.append(Diagnostic(
                    SEV_ERROR, "prefetch-window-crosses-write",
                    f"prefetch window [{lo}, {hi}] for {p!r} crosses a "
                    f"write by op #{i} ({op_.type}) — consumers after it "
                    f"would read a stale gathered copy",
                    op_index=i, op_type=op_.type, var=p,
                    pass_name="dp_prefetch_plan"))
                break
    return diags


# --------------------------------------------------------------------------
# pass gate: snapshot -> apply -> verify (motion hazards + new findings)
# --------------------------------------------------------------------------
def _diag_keys(diags: Sequence[Diagnostic]) -> set:
    return {d.key() for d in diags}


#: last absolute-sweep finding keys, memoized on (program, _version):
#: Pass.apply brackets every pass with a pre-sweep (snapshot) and a
#: post-sweep (verify_pass), so on an unchanged program pass k+1's
#: pre-sweep is exactly pass k's post-sweep — reuse it instead of
#: sweeping the whole program twice per pass
_sweep_cache: dict = {"ref": None, "version": None, "keys": None}


def _remember_sweep(program: Program, keys: set) -> None:
    _sweep_cache.update(ref=weakref.ref(program),
                        version=getattr(program, "_version", None),
                        keys=keys)


def _absolute_sweep_keys(program: Program) -> set:
    ref = _sweep_cache["ref"]
    version = getattr(program, "_version", None)
    if ref is not None and ref() is program and version is not None \
            and _sweep_cache["version"] == version:
        return _sweep_cache["keys"]
    keys = _diag_keys(check_dataflow(program) + check_nhwc(program)
                      + check_registry(program))
    _remember_sweep(program, keys)
    return keys


def snapshot(program: Program) -> dict:
    """Pre-pass state: per-block event lists (op object refs keep ids
    stable — removed ops stay alive for the comparison) plus the
    program's pre-existing finding keys, so the gate only fires on
    problems the pass INTRODUCED."""
    events = {blk.idx: block_events(blk) for blk in program.blocks}
    return {"events": events, "pre_keys": _absolute_sweep_keys(program)}


def _motion_hazards(before: List[tuple], after: List[tuple],
                    block_idx: int) -> List[Diagnostic]:
    """Observed-writer correspondence over ops carried across the pass."""
    diags: List[Diagnostic] = []
    before_ids = {id(op_) for op_, _, _ in before}
    after_ids = {id(op_) for op_, _, _ in after}
    carried = before_ids & after_ids

    def writer_maps(events):
        """op id -> {var -> writing op} for the last write BEFORE each
        op's position, and var -> last writer overall."""
        observed: Dict[int, Dict[str, Operator]] = {}
        last: Dict[str, Operator] = {}
        for op_, rs, ws in events:
            obs = {}
            for n in rs:
                if n in last:
                    obs[n] = last[n]
            observed[id(op_)] = obs
            for n in ws:
                last[n] = op_
        return observed, last

    obs_before, last_before = writer_maps(before)
    obs_after, last_after = writer_maps(after)
    reads_before = {id(op_): set(rs) for op_, rs, _ in before}
    pos_after = {id(op_): i for i, (op_, _, _) in enumerate(after)}
    pos_before = {id(op_): i for i, (op_, _, _) in enumerate(before)}

    for i, (op_, rs, ws) in enumerate(after):
        oid = id(op_)
        if oid not in carried:
            continue
        common = set(rs) & reads_before.get(oid, set())
        for n in common:
            wb = obs_before[oid].get(n)
            wa = obs_after[oid].get(n)
            if wa is wb:
                continue
            if wa is not None and id(wa) not in carried:
                continue  # pass-inserted producer: deliberate redirect
            was = (f"op #{pos_before[id(wb)]} ({wb.type}) of the "
                   f"pre-pass program" if wb is not None
                   else "the scope")
            now = (f"op #{pos_after[id(wa)]} ({wa.type})"
                   if wa is not None else "the scope (no write precedes it)")
            diags.append(Diagnostic(
                SEV_ERROR, "raw-war-hazard",
                f"op motion changed the value this op reads: {n!r} now "
                f"comes from {now}, was {was}", block_idx, i, op_.type,
                var=n))
    # WAW: the surviving final write to a var must come from the same
    # surviving op (a pass-inserted writer is a deliberate redirect)
    for n, wb in last_before.items():
        wa = last_after.get(n)
        if wa is None or wa is wb:
            continue
        if id(wa) not in carried or id(wb) not in carried:
            continue
        diags.append(Diagnostic(
            SEV_ERROR, "waw-hazard",
            f"op motion reordered the final write to {n!r}: now op "
            f"#{pos_after[id(wa)]} ({wa.type}), was ({wb.type})",
            block_idx, pos_after.get(id(wa)), wa.type, var=n))
    return diags


def verify_pass(snap: dict, program: Program, pass_name: str,
                raise_on_error: bool = True) -> List[Diagnostic]:
    """Post-pass verification: motion hazards against the snapshot plus
    any NEW absolute finding.  Raises VerifyError (naming the pass, op
    index and hazard) on error-severity findings."""
    diags: List[Diagnostic] = []
    for blk in program.blocks:
        before = snap["events"].get(blk.idx)
        if before is None:
            continue  # pass-created block: absolute checks still apply
        diags.extend(_motion_hazards(before, block_events(blk), blk.idx))
    post = check_dataflow(program) + check_nhwc(program) + \
        check_registry(program)
    _remember_sweep(program, _diag_keys(post))
    pre_keys = snap["pre_keys"]
    new = [d for d in post if d.key() not in pre_keys]
    for d in new:
        if d.code in ("orphaned-read", "subblock-capture", "use-before-def"):
            d.severity = SEV_ERROR  # pass-introduced: no scope excuse
    diags.extend(new)
    for d in diags:
        d.pass_name = d.pass_name or pass_name
    errors = [d for d in diags if d.severity == SEV_ERROR]
    if errors and raise_on_error:
        raise VerifyError(errors, pass_name)
    return diags


# --------------------------------------------------------------------------
# standalone entry (tools/progcheck.py, --verify tool flags, tests)
# --------------------------------------------------------------------------
def verify_program(program: Program, feed_names=(), fetch_names=(),
                   rules=("dataflow", "registry", "nhwc")
                   ) -> List[Diagnostic]:
    """Full absolute-check sweep over one program."""
    diags: List[Diagnostic] = []
    if "dataflow" in rules:
        diags.extend(check_dataflow(program, feed_names, fetch_names))
    if "registry" in rules:
        diags.extend(check_registry(program))
    if "nhwc" in rules:
        diags.extend(check_nhwc(program))
    return diags


def lint_or_raise(program: Program, feed_names=(), fetch_names=(),
                  where: str = "compile") -> None:
    """Absolute sweep raising VerifyError on error-severity findings —
    the shared final-program lint of the executor / DP compile paths
    (unregistered ops, conformance breaks and capture violations become
    one diagnostic instead of a mid-trace KeyError)."""
    errs = [d for d in verify_program(program, feed_names=set(feed_names),
                                      fetch_names=fetch_names)
            if d.severity == SEV_ERROR]
    if errs:
        raise VerifyError(errs, where)


def check_prefetch_plan_or_raise(ops: Sequence[Operator], block: Block,
                                 records: Sequence[dict],
                                 where: str = "prefetch_plan") -> None:
    """check_prefetch_plan, raising on error-severity findings."""
    bad = [d for d in check_prefetch_plan(ops, block, records)
           if d.severity == SEV_ERROR]
    if bad:
        raise VerifyError(bad, where)
