"""Graph IR: Program / Block / Operator / Variable.

Capability parity with the reference's serializable ProgramDesc IR
(reference: paddle/fluid/framework/framework.proto:40-216 and the Python
mirror python/paddle/fluid/framework.py — Program:3852, Block:2391,
Operator:1822, Variable:835).  Design differences, TPU-first:

* One level of objects, not two: in the reference a Python ``Variable``
  wraps a C++ ``VarDesc``; here the Python object *is* the desc, with JSON
  serialization for round-trips (``Program.serialize_to_string``).
* Compile-time shape inference runs at ``append_op`` time through the op
  registry (the analog of ``OpDesc::InferShape`` against the desc).
* Execution lowers whole blocks to jaxpr/XLA (see executor.py) instead of
  dispatching per-op kernels, so the IR carries no kernel-type information.
"""
from __future__ import annotations

import contextlib
import copy
import json
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import unique_name
from .dtype import VarType, convert_dtype, dtype_name

GRAD_SUFFIX = "@GRAD"
EMPTY_VAR_NAME = "@EMPTY@"


# --------------------------------------------------------------------------
# Variable
# --------------------------------------------------------------------------
class Variable:
    """A named slot in a Block (reference: framework.py:835 Variable /
    framework.proto VarDesc).  Holds static metadata only; values live in a
    Scope at run time or on a dygraph VarBase in eager mode."""

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        dtype=VarType.FP32,
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        type: VarType = VarType.LOD_TENSOR,
        is_data: bool = False,
        need_check_feed: bool = False,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else ()
        self.dtype = convert_dtype(dtype) if dtype is not None else None
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = VarType(type)
        self.is_data = is_data
        self.need_check_feed = need_check_feed
        # attached by layers for sharding annotation (TPU-native extension):
        self.sharding: Optional[tuple] = None

    # -- desc-ish API ------------------------------------------------------
    @property
    def ndim(self):
        return len(self.shape)

    def desc_dict(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape),
            "dtype": dtype_name(self.dtype) if self.dtype is not None else None,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "type": int(self.type),
            "is_data": self.is_data,
        }

    @staticmethod
    def from_desc_dict(block: "Block", d: dict) -> "Variable":
        cls = Parameter if d.get("is_parameter") else Variable
        var = cls.__new__(cls)
        Variable.__init__(
            var,
            block,
            name=d["name"],
            shape=d["shape"],
            dtype=d["dtype"],
            lod_level=d.get("lod_level", 0),
            persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            type=VarType(d.get("type", VarType.LOD_TENSOR)),
            is_data=d.get("is_data", False),
        )
        if isinstance(var, Parameter):
            var.trainable = d.get("trainable", True)
            var.optimize_attr = d.get("optimize_attr", {"learning_rate": 1.0})
            var.regularizer = None
            var.do_model_average = None
            var.is_distributed = False
        return var

    def __repr__(self):
        dt = dtype_name(self.dtype) if self.dtype is not None else "?"
        return f"var {self.name} : {self.type.name}.shape{self.shape}.dtype({dt})"

    __str__ = __repr__

    # numpy-ish sugar -------------------------------------------------------
    def astype(self, dtype):
        from ..layers import tensor as _tensor_layers

        return _tensor_layers.cast(self, dtype)

    @property
    def grad_name(self) -> str:
        return self.name + GRAD_SUFFIX

    # math operators are monkey-patched in layers/math_op_patch.py


class Parameter(Variable):
    """Trainable persistable variable (reference: framework.py:4962)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        kwargs["stop_gradient"] = kwargs.get("stop_gradient", False)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.do_model_average = kwargs.get("do_model_average", None)
        self.is_distributed = kwargs.get("is_distributed", False)

    def desc_dict(self):
        d = super().desc_dict()
        d["is_parameter"] = True
        d["trainable"] = self.trainable
        d["optimize_attr"] = self.optimize_attr
        return d


# --------------------------------------------------------------------------
# Operator
# --------------------------------------------------------------------------
class Operator:
    """An op node (reference: framework.py:1822 Operator / proto OpDesc).

    inputs/outputs are slot->list-of-var-names dicts; attrs is a plain dict
    (values: python scalars, lists, strings, VarType ints, Block refs stored
    as block indices — mirroring the reference's BLOCK attr type).
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = OrderedDict()
        self.outputs: Dict[str, List[str]] = OrderedDict()
        self.attrs: Dict[str, Any] = dict(attrs or {})
        for slot, vars_ in (inputs or {}).items():
            self.inputs[slot] = _to_name_list(vars_)
        for slot, vars_ in (outputs or {}).items():
            self.outputs[slot] = _to_name_list(vars_)

    # -- accessors mirroring the reference OpDesc API ----------------------
    def input(self, slot: str) -> List[str]:
        return list(self.inputs.get(slot, []))

    def output(self, slot: str) -> List[str]:
        return list(self.outputs.get(slot, []))

    @property
    def input_arg_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def has_attr(self, name: str) -> bool:
        return name in self.attrs

    def _set_attr(self, name: str, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    set_attr = _set_attr

    def rename_input(self, old: str, new: str):
        for slot, names in self.inputs.items():
            self.inputs[slot] = [new if n == old else n for n in names]
        self.block.program._bump_version()

    def rename_output(self, old: str, new: str):
        for slot, names in self.outputs.items():
            self.outputs[slot] = [new if n == old else n for n in names]
        self.block.program._bump_version()

    def desc_dict(self) -> dict:
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": _attrs_to_json(self.attrs),
        }

    @staticmethod
    def from_desc_dict(block: "Block", d: dict) -> "Operator":
        return Operator(
            block,
            d["type"],
            inputs=d.get("inputs", {}),
            outputs=d.get("outputs", {}),
            attrs=_attrs_from_json(d.get("attrs", {})),
        )

    def __repr__(self):
        ins = ", ".join(f"{k}={v}" for k, v in self.inputs.items())
        outs = ", ".join(f"{k}={v}" for k, v in self.outputs.items())
        return f"{{Op({self.type}) inputs({ins}) outputs({outs})}}"

    __str__ = __repr__


def _to_name_list(vars_) -> List[str]:
    if vars_ is None:
        return []
    if isinstance(vars_, (Variable, str)):
        vars_ = [vars_]
    out = []
    for v in vars_:
        out.append(v.name if isinstance(v, Variable) else str(v))
    return out


_JSONABLE = (bool, int, float, str, type(None))


def _attrs_to_json(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, VarType):
            out[k] = {"__vartype__": int(v)}
        elif isinstance(v, Block):
            out[k] = {"__block__": v.idx}
        elif isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (list, tuple)):
            out[k] = [int(x) if isinstance(x, np.integer) else x for x in v]
        elif isinstance(v, dict):
            # plain dict attr (e.g. grad ops' __fwd_out_slots__); wrapped
            # so _attrs_from_json can tell it apart from the typed markers
            out[k] = {"__dict__": _attrs_to_json(v)}
        elif isinstance(v, np.integer):
            out[k] = int(v)
        elif isinstance(v, np.floating):
            out[k] = float(v)
        elif isinstance(v, _JSONABLE):
            out[k] = v
        else:
            out[k] = repr(v)  # last resort; non-round-trippable
    return out


def _attrs_from_json(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__vartype__" in v:
            out[k] = VarType(v["__vartype__"])
        elif isinstance(v, dict) and "__block__" in v:
            out[k] = ("__block__", v["__block__"])  # resolved by Program loader
        elif isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
        elif isinstance(v, dict) and "__dict__" in v:
            out[k] = _attrs_from_json(v["__dict__"])
        else:
            out[k] = v
    return out


# --------------------------------------------------------------------------
# Block
# --------------------------------------------------------------------------
class Block:
    """Reference: framework.py:2391 / proto BlockDesc."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.forward_block_idx = -1
        self.vars: "OrderedDict[str, Variable]" = OrderedDict()
        self.ops: List[Operator] = []

    # -- var management ----------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        self.program._bump_version()
        return var

    def create_parameter(self, **kwargs) -> Parameter:
        param = Parameter(self, **kwargs)
        self.vars[param.name] = param
        self.program._bump_version()
        return param

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise ValueError(f"var {name!r} not in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = (
                self.program.blocks[blk.parent_idx]
                if blk.parent_idx >= 0
                else None
            )
        return None

    def var_recursive(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError(f"var {name!r} not found (recursively)")
        return v

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def _remove_var(self, name: str):
        self.vars.pop(name, None)
        self.program._bump_version()

    def _rename_var(self, old: str, new: str):
        """Rename the var AND every reference to it: this block's ops,
        their ``op_role_var`` attr lists, and ops in DESCENDANT blocks
        (cond/while bodies capture parent vars by name) unless a block
        on the path declares its own ``old`` — a shadowed name refers
        to the local var, not this one.  Renaming only the local op
        list (the pre-verifier behavior) left orphaned references the
        static verifier now flags as ``orphaned-read``."""
        var = self.vars.pop(old)
        var.name = new
        self.vars[new] = var
        blocks = [self]
        for blk in self.program.blocks:
            if blk is self:
                continue
            # visible from blk iff self is on blk's parent chain with no
            # intermediate (or local) declaration of `old` shadowing it
            cur, shadowed, on_chain = blk, old in blk.vars, False
            while cur is not None:
                parent = cur.parent_block
                if parent is self:
                    on_chain = True
                    break
                if parent is not None and old in parent.vars:
                    shadowed = True
                cur = parent
            if on_chain and not shadowed:
                blocks.append(blk)
        for blk in blocks:
            for op in blk.ops:
                op.rename_input(old, new)
                op.rename_output(old, new)
                rv = op.attrs.get("op_role_var")
                if rv and old in rv:
                    op.attrs["op_role_var"] = [
                        new if n == old else n for n in rv]
        self.program._bump_version()

    # -- op management -----------------------------------------------------
    def append_op(
        self, type: str, inputs=None, outputs=None, attrs=None, index=None
    ) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        dev = self.program._current_device
        if dev is not None and "op_device" not in op.attrs:
            op.attrs["op_device"] = dev
        if "op_callstack" not in op.attrs:
            # build-site callstack for error attribution (reference:
            # framework/op_call_stack.cc + op_proto_maker OpCreationCallstack);
            # user frames only — paddle_tpu internals are noise.  Walk raw
            # frames innermost-out and stop after 3 user frames so
            # transpiler/optimizer-inserted ops (all internal frames) pay
            # almost nothing and no source lines are read eagerly.
            import sys

            frames = []
            f = sys._getframe(1)
            while f is not None and len(frames) < 3:
                fname = f.f_code.co_filename
                if "paddle_tpu" not in fname:
                    frames.append(f'File "{fname}", line {f.f_lineno}, '
                                  f"in {f.f_code.co_name}")
                f = f.f_back
            if frames:
                op.attrs["op_callstack"] = frames[::-1]  # outermost first
        from ..ops import registry  # local import to avoid cycles

        registry.infer_shape(op, self)
        if index is None:
            self.ops.append(op)
        else:
            self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        return self.append_op(type, inputs, outputs, attrs, index=index)

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        return self.append_op(type, inputs, outputs, attrs, index=0)

    def _remove_op(self, index: int):
        del self.ops[index]
        self.program._bump_version()

    @property
    def parent_block(self) -> Optional["Block"]:
        return (
            self.program.blocks[self.parent_idx] if self.parent_idx >= 0 else None
        )

    def desc_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": [v.desc_dict() for v in self.vars.values()],
            "ops": [op.desc_dict() for op in self.ops],
        }

    def __repr__(self):
        lines = [f"block {self.idx} (parent {self.parent_idx})"]
        lines += [f"  {v}" for v in self.vars.values()]
        lines += [f"  {op}" for op in self.ops]
        return "\n".join(lines)


# --------------------------------------------------------------------------
# Program
# --------------------------------------------------------------------------
class Program:
    """Reference: framework.py:3852 / proto ProgramDesc."""

    _uid_counter = 0

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        # monotonic program identity for executor caches: id() is reused
        # after GC, so a long-lived Executor serving short-lived Programs
        # could hit a stale compiled entry keyed on id(program)
        Program._uid_counter += 1
        self._uid = Program._uid_counter
        self._op_role = 0  # OpRole.Forward
        self._is_distributed = False
        self._seed_counter = 0
        # distillation of reference's Program attributes used by transpilers
        self._parameters_on_pservers = None
        self._sharding_spec = None  # TPU-native: program-level default sharding
        # fluid.device_guard state (reference: framework.py:5420): ops
        # appended inside the guard carry an `op_device` attr; the pipeline
        # splitter groups contiguous annotations into stages.
        self._current_device = None
        self._pipeline_opt = None

    # -- blocks ------------------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        if parent_idx is None:
            parent_idx = self.current_block_idx
        blk = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        self._bump_version()
        return blk

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    def _next_seed(self) -> int:
        """Deterministic per-op seed allocator for random ops."""
        self._seed_counter += 1
        return self._seed_counter

    # -- parameters / io ---------------------------------------------------
    def all_parameters(self) -> List[Parameter]:
        return [p for b in self.blocks for p in b.all_parameters()]

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        """Deep clone (reference: framework.py Program.clone).  With
        ``for_test=True``, backward/optimize/lr-sched-role ops are pruned
        (reference: framework.py:4194-4209 — cloning after ``minimize()``
        yields a forward-only program) and the surviving ops flip their
        ``is_test`` attr (dropout/batch_norm change behavior)."""
        p = Program.from_desc_dict(self.desc_dict())
        p.random_seed = self.random_seed
        if for_test:
            # roles are recorded as op attrs at build time, so the clone
            # needs no graph analysis to drop the training tail.  Note
            # OpRole.RPC (3) overlaps the Backward|Optimize bits and is
            # pruned too — an RPC op has no place in a test program.
            from ..backward import OpRole

            role_mask = OpRole.Backward | OpRole.Optimize | OpRole.LRSched
            for blk in p.blocks:
                blk.ops[:] = [
                    op for op in blk.ops
                    if not (int(op.attrs.get("op_role", 0)) & role_mask)
                ]
                for op in blk.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
            p._bump_version()
        return p

    # -- serialization -----------------------------------------------------
    def desc_dict(self) -> dict:
        return {
            "version": 1,
            "blocks": [b.desc_dict() for b in self.blocks],
        }

    @staticmethod
    def from_desc_dict(d: dict) -> "Program":
        p = Program()
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd.get("parent_idx", -1))
            blk.forward_block_idx = bd.get("forward_block_idx", -1)
            for vd in bd["vars"]:
                var = Variable.from_desc_dict(blk, vd)
                blk.vars[var.name] = var
            p.blocks.append(blk)
        # ops in a second pass so block-attr refs can resolve
        for bd, blk in zip(d["blocks"], p.blocks):
            for od in bd["ops"]:
                op = Operator.from_desc_dict(blk, od)
                for k, v in list(op.attrs.items()):
                    if isinstance(v, tuple) and len(v) == 2 and v[0] == "__block__":
                        op.attrs[k] = p.blocks[v[1]]
                blk.ops.append(op)
        if not p.blocks:
            p.blocks = [Block(p, 0)]
        p.current_block_idx = 0
        return p

    def serialize_to_string(self) -> bytes:
        return json.dumps(self.desc_dict()).encode("utf-8")

    @staticmethod
    def parse_from_string(s: bytes) -> "Program":
        return Program.from_desc_dict(json.loads(s.decode("utf-8")))

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)

    __str__ = __repr__


# --------------------------------------------------------------------------
# default program / guards (reference: framework.py:5167-5420)
# --------------------------------------------------------------------------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


@contextlib.contextmanager
def device_guard(device=None):
    """reference: framework.py:5420 fluid.device_guard.  Ops appended
    inside the guard are annotated with ``op_device``; PipelineOptimizer
    uses contiguous annotations as stage boundaries."""
    prog = _main_program
    prev = prog._current_device
    prog._current_device = device
    try:
        yield
    finally:
        prog._current_device = prev


@contextlib.contextmanager
def name_scope(prefix: str):
    """API-compat no-op grouping scope (reference: framework.py name_scope)."""
    yield


# -- dygraph mode flag (reference: framework.py:180 in_dygraph_mode) --------
_dygraph_tracer = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer is not None


def _set_dygraph_tracer(tracer):
    global _dygraph_tracer
    _dygraph_tracer = tracer


def _current_tracer():
    return _dygraph_tracer
