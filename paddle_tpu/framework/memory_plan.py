"""Static liveness-based HBM memory planner over Program/Block.

Every headline memory claim this repo makes (the ZeRO ladder's "1/ndev
bytes per device", the KV pool's residency, the r14 fusion's saved
traffic) was, until now, an assertion derived by hand.  This module is
the memory model those claims check against: a pure static analysis
that reuses the verifier's per-op read/write sets
(framework/verifier.py ``op_reads_writes`` — registry OpDef metadata,
in-place ops write their inputs via output==input name) to compute

* per-var **lifetime intervals** over the op list (state is resident
  from op 0; an activation lives from its defining write to its last
  read; fetches and persistable writes live to the end),
* the per-op **live-set byte timeline** (per device), and
* the **peak-HBM op** — the op index where modeled residency tops out,
  with the top live vars at that point.

It is aware of the structural facts that make a naive sum-of-var-bytes
wrong here:

* **donated input/output aliasing** (the executor step session): an
  in-place state update reuses its input buffer under buffer donation;
  with donation off (``FLAGS_tpu_donate_buffers=0`` /
  ``FLAGS_tpu_step_session=0``) the old and new copies coexist and the
  model charges the extra copy from the update to the end of the step;
* **ZeRO row-sharding** (``FLAGS_dp_sharding``): stage-3 parameters and
  stage>=1 optimizer state count 1/ndev per device (same partition-rule
  engine + planning helpers as parallel/data_parallel.py — shared, so
  the model and the runtime cannot drift); stage>=2 gradients count
  1/ndev from their
  reduce-scatter point (shard_map path: after the
  ``c_fused_reduce_scatter`` op; pjit path: throughout, GSPMD never
  materializes the full gradient);
* **fused gradient buckets**: ``c_fused_allreduce`` /
  ``c_fused_reduce_scatter`` concatenate their members into one flat
  transient buffer inside the lowering — modeled as an explicit per-op
  transient (see :data:`TRANSIENT_BYTES`);
* **ZeRO-3 prefetch windows**: a gathered parameter is transiently
  full-size for exactly its window — the records come from
  ``compiled._prefetch_plan`` (or are re-derived with
  ``data_parallel._plan_param_prefetch`` for standalone analysis);
  with depth 0 the just-in-time gather bumps each consumer op instead;
* **while→scan carry reuse**: a sub-block's vars are NOT summed into
  the parent — the loop body's own peak (carries reuse their buffers
  across iterations under scan) is charged as a transient at the loop
  op;
* **fixed resident blocks** (the serving KV page pool): scope-resident
  persistable state the program reads (the pools are block vars of the
  decode program, so they fall out of the state analysis naturally);
  ``extra_resident`` adds engine-level blocks the program cannot see.

Three surfaces consume the plan:

1. compile time — ``Executor._compile`` and the DP compile path attach
   ``_memory_plan``, publish the ``hbm_modeled_peak_bytes`` gauge, and
   enforce ``FLAGS_hbm_budget_mb`` (warn; ``FLAGS_hbm_budget_strict``
   raises :class:`MemoryBudgetError` naming the peak op and the top-10
   live vars);
2. runtime reconciliation — ``utils/memory.py`` measures the per-step
   peak (PJRT allocator counters on chip, a shard-aware live-arrays
   census on the CPU proxy) and ``tools/mem_report.py`` prints modeled
   vs measured side by side;
3. the failure path — :func:`record_oom_debris` dumps plan + telemetry
   + trace to ``FLAGS_oom_debris_dir`` when the executor catches a
   ``RESOURCE_EXHAUSTED``, so a chip OOM is diagnosable post-mortem.

The analysis is pure: it registers no ops, mutates no program, and
changes no numerics (pinned by test).
"""
from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .core import Block, Program
from .dtype import to_numpy_dtype
from .verifier import EMPTY, op_reads_writes

__all__ = [
    "MemoryPlan", "MemoryBudgetError", "plan_memory", "var_bytes",
    "check_budget", "budget_bytes", "memory_audit", "transient_bytes",
    "TRANSIENT_BYTES", "AUDITED_DEFAULT", "is_resource_exhausted",
    "record_oom_debris", "emit_trace_counters",
]

_MB = float(1 << 20)


# ==========================================================================
# per-var byte model
# ==========================================================================
def var_bytes(block: Block, name: str, assumed_batch: int = 64
              ) -> Optional[int]:
    """Full (unsharded) bytes of one var: shape x dtype itemsize, with
    dynamic (-1) dims standing in as ``assumed_batch`` (the cost-model
    convention).  None when the var is undeclared or shapeless (host
    ids, LoD metadata) — such names cost the model nothing."""
    var = block._find_var_recursive(name)
    if var is None or var.shape is None or var.dtype is None:
        return None
    n = 1
    for d in var.shape:
        if d is None:
            return None
        d = int(d)
        n *= assumed_batch if d < 0 else max(d, 1)
    try:
        itemsize = np.dtype(to_numpy_dtype(var.dtype)).itemsize
    except Exception:
        return None
    return int(n) * int(itemsize)


#: var classes the plan reports (resident-vs-transient breakdown)
CLASSES = ("param", "opt_state", "grad", "feed", "kv_pool", "state",
           "activation")


def _classify(name: str, *, params: set, opt_state: set, feeds: set,
              resident: bool) -> str:
    if name in feeds:
        return "feed"
    if name in params:
        return "param"
    if name in opt_state:
        return "opt_state"
    if name.endswith("@GRAD") or "@GRAD@" in name:
        return "grad"
    if name.startswith("kv_k_") or name.startswith("kv_v_"):
        # the paged K/V pools: one fixed device block per layer per
        # side, sized by the ALLOCATOR's pool shape — page-level
        # bookkeeping (r19 CoW sharing included) happens INSIDE this
        # block, so a page mapped by N sequences is modeled once, and
        # the modeled kv_pool bytes agree with the runtime census
        # whether or not prefixes are shared (pinned by test)
        return "kv_pool"
    return "state" if resident else "activation"


# ==========================================================================
# per-op transient model + the coverage-gate audit surface
# ==========================================================================
def _fused_bucket_payload(op_, block, assumed_batch):
    total = 0
    for n in op_.inputs.get("X", []):
        b = var_bytes(block, n, assumed_batch)
        if b:
            total += b
    return total


def _t_fused_allreduce(op_, block, ndev, assumed_batch):
    """Flat concat of the bucket (one payload) + the reduced flat
    result (one payload) before it is sliced back per member."""
    return 2 * _fused_bucket_payload(op_, block, assumed_batch)


def _t_fused_reduce_scatter(op_, block, ndev, assumed_batch):
    """Flat (nranks, total/nranks) payload + the 1/ndev scattered
    shard."""
    p = _fused_bucket_payload(op_, block, assumed_batch)
    return p + (p // max(ndev, 1))


def _t_allgather(op_, block, ndev, assumed_batch):
    """The gathered result is ndev x the input — the declared output
    var usually carries the gathered shape already, but the transient
    concat buffer is charged explicitly so a shapeless output cannot
    hide it."""
    return ndev * _fused_bucket_payload(op_, block, assumed_batch)


def _t_coalesce(op_, block, ndev, assumed_batch):
    """coalesce_tensor materializes one flat FusedOutput over all
    inputs."""
    total = 0
    for names in op_.inputs.values():
        for n in names:
            b = var_bytes(block, n, assumed_batch)
            if b:
                total += b
    return total


def _t_paged_attention(op_, block, ndev, assumed_batch):
    """The CPU gather fallback materializes per-sequence K/V gathers of
    the block-table width: ~2 x (num_seqs, table_width*page_size,
    head_dim) — bounded above by 2 x the pool bytes it gathers from.
    (On TPU the Pallas kernel streams pages; this is the fallback's
    worst case, which is the honest CPU-proxy number.)"""
    total = 0
    for slot in ("KCache", "VCache"):
        for n in op_.inputs.get(slot, []):
            b = var_bytes(block, n, assumed_batch)
            if b:
                total += b
    return total


def _t_sample_token(op_, block, ndev, assumed_batch):
    """The top-k/top-p filters sort the logits rows and build filtered
    copies before the categorical draw: ~3 logits-sized f32 temporaries
    (sorted values, cumulative probs, masked logits) beyond the
    (num_rows,) output — charged explicitly because the output is tiny
    and would hide them under the default."""
    total = 0
    for n in op_.inputs.get("Logits", []):
        b = var_bytes(block, n, assumed_batch)
        if b:
            total += 3 * b
    return total


def _t_subblock(op_, block, ndev, assumed_batch):
    """Control-flow ops: the body's own peak (computed over vars the
    sub-block declares — loop carries alias the parent's values under
    the scan lowering, so they are charged once, in the parent)."""
    total = 0
    for v in op_.attrs.values():
        if isinstance(v, Block):
            total += _subblock_peak(v, assumed_batch)
    return total


def _subblock_peak(blk: Block, assumed_batch: int) -> int:
    """Live-set peak of one sub-block counting only its OWN vars
    (captures live in an ancestor are already charged there).  Carries
    reuse their buffers across iterations (while→scan), so one
    iteration's live set IS the loop's contribution."""
    events = [(i,) + op_reads_writes(op_) for i, op_ in enumerate(blk.ops)]
    own = set(blk.vars)
    first: Dict[str, int] = {}
    last: Dict[str, int] = {}
    for i, rs, ws in events:
        for n in ws:
            if n in own:
                first.setdefault(n, i)
                last[n] = i
        for n in rs:
            if n in own:
                last[n] = i
                first.setdefault(n, 0)  # read-before-write: carry-like
    n_ops = max(len(blk.ops), 1)
    diff = [0] * (n_ops + 1)
    for n, lo in first.items():
        b = var_bytes(blk, n, assumed_batch)
        if not b:
            continue
        hi = last.get(n, lo)
        diff[lo] += b
        diff[hi + 1] -= b
    peak = cur = 0
    for i in range(n_ops):
        cur += diff[i]
        peak = max(peak, cur)
    # nested blocks
    for op_ in blk.ops:
        for v in op_.attrs.values():
            if isinstance(v, Block):
                peak = max(peak, _subblock_peak(v, assumed_batch))
    return peak


#: op type -> fn(op, block, ndev, assumed_batch) -> extra transient
#: device bytes the op's lowering materializes BEYOND its declared
#: inputs/outputs.  This is the planner's explicit byte model — the
#: analog of cost_model._EPILOGUE_TRAFFIC, and like it, guarded by the
#: op-sweep coverage gate (tests/test_memory_plan.py): a registered op
#: must either appear here or in AUDITED_DEFAULT below, so a new op
#: with a hidden full-size temporary cannot ride the silent default.
TRANSIENT_BYTES = {
    "c_fused_allreduce": _t_fused_allreduce,
    "c_fused_reduce_scatter": _t_fused_reduce_scatter,
    "c_allgather": _t_allgather,
    "c_concat": _t_allgather,          # all-gather then concat: same peak
    "coalesce_tensor": _t_coalesce,
    "paged_attention": _t_paged_attention,
    "sample_token": _t_sample_token,
    "while": _t_subblock,
    "while_loop": _t_subblock,
    "recurrent": _t_subblock,
    "conditional_block": _t_subblock,
    "conditional_block_infer": _t_subblock,
    "cond": _t_subblock,
    "run_program": _t_subblock,
}

#: ops audited (r15) to have NO device transient beyond their declared
#: inputs/outputs: the lowering is jnp/lax compositions whose
#: intermediates are op-output-sized or smaller, or the op is host-side
#: (RPC, IO, LoD bookkeeping) and owns no device buffer at all.  An op
#: in neither table fails the coverage sweep — classify it when you
#: register it.  Grad ops derive coverage from their forward op (the
#: generic-vjp backward replays the forward's lowering).
AUDITED_DEFAULT = frozenset("""
abs accuracy acos adadelta adagrad adam adamax adamw adaptive_pool3d
add_position_encoding addmm affine_channel affine_grid allclose
amp_check_finite_and_scale anchor_generator arg_max arg_min argsort
array_to_lod_tensor asin assert assert_op assign assign_value atan
attention_lstm auc average_accumulates batch_fc batch_norm batched_iou
bce_loss beam_gather_states beam_search beam_search_decode bicubic_interp
bilinear_interp bilinear_tensor_product bipartite_match bmm box_clip
box_coder box_decoder_and_assign bpr_loss brelu broadcast_tensors cast ceil
center_loss checkpoint_notify cholesky chunk_eval clip clip_by_norm
collect_fpn_proposals concat conv2d conv2d_transpose conv3d conv3d_transpose
conv_shift cos cos_sim cosh create_array create_custom_reader crf_decoding
crop crop_tensor cross cross_entropy cross_entropy2 cross_entropy_grad2
ctc_align cudnn_lstm cumsum cvm cvm_grad data_norm decayed_adagrad
deformable_conv deformable_conv_v1 deformable_psroi_pooling
deformable_roi_pooling delete_var density_prior_box depthwise_conv2d
depthwise_conv2d_transpose dequantize dequantize_abs_max dequantize_linear
dequantize_log dequeue detection_map dgc dgc_clip_by_norm dgc_momentum diag
diag_embed diag_v2 dist distribute_fpn_proposals distributed_lookup_table
distributed_lookup_table_grad dot dpsgd dropout dropout_grad dynamic_gru
dynamic_lstm dynamic_lstmp edit_distance einsum elementwise_add
elementwise_div elementwise_floordiv elementwise_max elementwise_min
elementwise_mod elementwise_mul elementwise_pow elementwise_sub elu
embedding enqueue equal erf exp expand expand_as expand_v2 expm1 eye
fake_channel_wise_dequantize_max_abs fake_channel_wise_quantize_abs_max
fake_channel_wise_quantize_dequantize_abs_max fake_dequantize_max_abs
fake_init fake_quantize_abs_max fake_quantize_dequantize_abs_max
fake_quantize_dequantize_moving_average_abs_max
fake_quantize_moving_average_abs_max fake_quantize_range_abs_max fc feed
fetch fetch_barrier fill fill_any_like fill_constant
fill_constant_batch_size_like fill_zeros_like fill_zeros_like2
filter_by_instag flatten flatten2 flatten_contiguous_range flip floor
frobenius_norm fsp ftrl gather gather_nd gather_tree gaussian_random
gaussian_random_batch_size_like gelu gen_nccl_id generate_mask_labels
generate_proposal_labels generate_proposals geo_sgd get_places
get_tensor_from_selected_rows global_step_counter greater_equal
greater_than grid_sampler group_norm gru gru_unit hard_shrink hard_sigmoid
hard_swish hash hierarchical_sigmoid hinge_loss histogram huber_loss
im2sequence increment index_sample index_select inplace_abn instance_norm
inverse iou_similarity is_empty isfinite isfinite_v2 isinf isinf_v2 isnan
isnan_v2 kldiv_loss kron l1_norm label_smooth lamb lars_momentum layer_norm
leaky_relu less_equal less_than linear_chain_crf linear_interp linspace
listen_and_serv load load_combine locality_aware_nms lod_array_length
lod_rank_table lod_reset lod_tensor_to_array log log10 log1p log2 log_loss
log_softmax logical_and logical_not logical_or logical_xor logsigmoid
logsumexp lookup_sparse_table lookup_table lookup_table_dequant
lookup_table_sparse_grad lookup_table_v2 lrn lstm lstm_unit lstmp
margin_rank_loss masked_select match_matrix_tensor matmul matmul_v2
matmul_with_flatten max_pool2d_with_index max_pool3d_with_index
max_sequence_len maximum maxout mean mean_iou memcpy merge_ids
merge_lod_tensor merge_lod_tensor_infer merge_selected_rows meshgrid
memcpy_d2h memcpy_h2d
mine_hard_examples minimum minus modified_huber_loss momentum
moving_average_abs_max_scale mse_loss mul multiclass_nms multiclass_nms2
multihead_matmul multiplex nce nearest_interp nll_loss norm not_equal
one_hot one_hot_v2 p_norm pad pad2d pad3d pad_constant_like partial_concat
partial_sum pixel_shuffle polygon_box_transform pool2d pool3d
positive_negative_pair pow precision_recall prefetch prelu print prior_box
proximal_adagrad proximal_gd prroi_pool psroi_pool pull_sparse
pull_sparse_v2 push_dense push_sparse push_sparse_v2 py_func py_func_grad
pyramid_hash quantize quantize_linear queue_generator randint random_crop
randperm range rank_attention rank_loss read read_from_array reciprocal
recv recv_save reduce_all reduce_any reduce_max reduce_mean reduce_min
reduce_prod reduce_sum ref_by_trainer_id relu relu6 reorder_lod_tensor_by_rank
requantize reshape reshape2 retinanet_detection_output
retinanet_target_assign reverse rmsprop rnn_memory_helper roi_align
roi_perspective_transform roi_pool roll round row_conv rpn_target_assign
rsqrt sample_logits sampled_softmax_with_cross_entropy sampling_id save
save_combine scale scatter scatter_nd_add seed selu send send_barrier
sequence_concat sequence_conv sequence_enumerate sequence_erase
sequence_expand sequence_expand_as sequence_mask sequence_pad sequence_pool
sequence_reshape sequence_reverse sequence_scatter sequence_slice
sequence_softmax sequence_topk_avg_pooling sequence_unpad sgd shape
shard_index share_data shrink_rnn_memory shuffle_batch shuffle_channel
sigmoid sigmoid_cross_entropy_with_logits sigmoid_focal_loss sign silu
similarity_focus sin sinh size slice smooth_l1_loss soft_relu softmax
softmax_with_cross_entropy softmax_with_cross_entropy_grad softplus
softsign space_to_depth spectral_norm split split_byref split_ids
split_lod_tensor split_selected_rows spp sqrt square squared_l2_distance
squared_l2_norm squeeze squeeze2 ssd_loss_core stack stanh strided_slice
sum swish sync_batch_norm tan tanh tanh_shrink target_assign tdm_child
tdm_sampler teacher_student_sigmoid_loss temporal_shift tensor_array_pop
tensor_array_to_tensor thresholded_relu tile top_k top_k_v2 trace transpose
transpose2 tree_conv tril_triu trilinear_interp truncated_gaussian_random
unbind unfold uniform_random uniform_random_batch_size_like unique
unique_with_counts unpool unsqueeze unsqueeze2 unstack
update_loss_scaling var_conv_2d warpctc
where where_index while_loop_grad write_to_array yolo_box yolov3_loss
select_input select_output kv_cache_append kv_dequant
allreduce alltoall barrier broadcast c_allreduce_max c_allreduce_min
c_allreduce_prod c_allreduce_sum c_broadcast c_comm_init c_comm_init_all
c_gen_nccl_id c_identity c_reducescatter c_split c_sync_calc_stream
c_sync_comm_stream c_wait_calc_stream c_wait_comm_stream
fused_adam fused_batch_norm_act fused_batch_norm_act_grad
fused_bn_add_activation fused_bn_add_activation_grad fused_conv_bn_act
fused_conv_bn_act_grad fused_elemwise_activation
fused_embedding_eltwise_layernorm fused_embedding_fc_lstm
fused_embedding_seq_pool fused_fc_elementwise_layernorm
fused_matmul_bias_act fused_matmul_bias_act_grad fused_momentum
fused_multihead_attention fused_multihead_attention_grad fused_sgd
fusion_gru fusion_lstm fusion_repeated_fc_relu fusion_seqconv_eltadd_relu
fusion_seqexpand_concat_fc fusion_seqpool_concat fusion_seqpool_cvm_concat
fusion_squared_mat_sub fusion_transpose_flatten_concat
""".split())
# Audit notes (what kept suspects OFF the default list): in-place
# psum-style allreduces write their input (no second buffer);
# `kv_cache_append` scatters in place into the donated pool;
# `kv_dequant` is an elementwise cast(+scale) into its declared slot;
# `c_identity`/`c_split` are views.  ON the explicit table instead:
# fused bucket collectives (flat concat payload), `c_allgather` /
# `c_concat` (ndev x payload), `coalesce_tensor` (flat FusedOutput),
# `paged_attention` (CPU fallback's per-sequence K/V gathers), and
# every sub-block op (the body's peak is invisible to the parent's
# declared slots).


def memory_audit(op_type: str) -> str:
    """Coverage verdict for one op type: ``"explicit"`` (entry in
    :data:`TRANSIENT_BYTES`), ``"default"`` (on the audited list, or a
    (higher-order) grad of a covered forward op — the generic-vjp
    backward replays the forward's lowering), ``"custom"`` (registered
    at runtime through utils/custom_op.py — the author's contract, not
    auditable statically), else ``"unclassified"`` — which the
    op-sweep-style gate turns into a test failure."""
    t = op_type
    while True:
        if t in TRANSIENT_BYTES:
            return "explicit" if t == op_type else "default"
        if t in AUDITED_DEFAULT:
            return "default"
        try:
            from ..utils.custom_op import CUSTOM_REGISTERED

            if t in CUSTOM_REGISTERED:
                return "custom"
        except Exception:
            pass
        if not t.endswith("_grad"):
            return "unclassified"
        t = t[: -len("_grad")]


def transient_bytes(op_, block: Block, ndev: int = 1,
                    assumed_batch: int = 64) -> int:
    """Extra transient device bytes op_'s lowering materializes beyond
    its declared inputs/outputs (0 for audited-default ops)."""
    fn = TRANSIENT_BYTES.get(op_.type)
    if fn is None:
        return 0
    try:
        return int(fn(op_, block, ndev, assumed_batch))
    except Exception:
        return 0


def _relief_mode() -> str:
    """The configured FLAGS_memory_relief mode ("off" default)."""
    from ..utils.flags import flag

    try:
        return str(flag("memory_relief", "off") or "off")
    except Exception:
        return "off"


#: host-staging suffix the memory_relief_pass gives its offloaded
#: copies: a ``...@D2H`` var lives in host RAM between the paired
#: memcpy_d2h / memcpy_h2d ops and holds ZERO device bytes — the whole
#: point of the offload fix
HOST_STAGE_SUFFIX = "@D2H"


# ==========================================================================
# the plan
# ==========================================================================
class MemoryBudgetError(RuntimeError):
    """Raised when FLAGS_hbm_budget_mb is exceeded under
    FLAGS_hbm_budget_strict."""


class MemoryPlan:
    """One program's modeled HBM footprint (per device)."""

    __slots__ = ("peak_bytes", "peak_op_index", "peak_op_type", "timeline",
                 "resident_bytes", "resident_by_class", "per_var",
                 "transients", "top_at_peak", "ndev", "stage", "donate",
                 "path", "assumed_batch", "n_ops", "extra_resident_bytes",
                 "prefetch_windows", "relief", "relief_candidates")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    # -- views -------------------------------------------------------------
    @property
    def peak_mb(self) -> float:
        return self.peak_bytes / _MB

    @property
    def resident_mb(self) -> float:
        return self.resident_bytes / _MB

    def top_live_at_peak(self, k: int = 10) -> List[Tuple[str, int]]:
        return list(self.top_at_peak[:k])

    def as_dict(self, top: int = 10) -> dict:
        return {
            "peak_bytes": int(self.peak_bytes),
            "peak_mb": round(self.peak_mb, 3),
            "peak_op": {"index": self.peak_op_index,
                        "type": self.peak_op_type},
            "resident_bytes": int(self.resident_bytes),
            "resident_mb": round(self.resident_mb, 3),
            "resident_by_class": {k: int(v) for k, v in
                                  sorted(self.resident_by_class.items())},
            "extra_resident_bytes": int(self.extra_resident_bytes),
            "top_live_at_peak": [
                {"var": n, "bytes": int(b), "class": c}
                for n, b, c in self.top_live_at_peak(top)],
            "transient_peak_bytes": max(
                (t["bytes"] for t in self.transients), default=0),
            "n_transients": len(self.transients),
            "prefetch_windows": self.prefetch_windows,
            "n_ops": self.n_ops,
            "ndev": self.ndev,
            "stage": self.stage,
            "path": self.path,
            "donate": bool(self.donate),
            "assumed_batch": self.assumed_batch,
            # relief decision table (memory_relief_pass) — the OOM
            # debris plan.json carries it for free; with the pass off
            # the entry says so explicitly
            "relief": (self.relief if self.relief is not None
                       else {"mode": _relief_mode(), "engaged": False}),
        }

    def format_table(self, top: int = 10) -> str:
        d = self.as_dict(top)
        lines = [
            f"modeled peak: {d['peak_mb']:.3f} MB at op "
            f"#{d['peak_op']['index']} ({d['peak_op']['type']}) over "
            f"{d['n_ops']} ops  [ndev={d['ndev']} stage={d['stage']} "
            f"path={d['path']} donate={d['donate']}]",
            f"resident: {d['resident_mb']:.3f} MB  "
            + "  ".join(f"{k}={v / _MB:.3f}MB"
                        for k, v in d["resident_by_class"].items() if v),
            f"{'Top live vars at peak':<44} {'MB':>10}  class",
        ]
        for row in d["top_live_at_peak"]:
            lines.append(f"{row['var'][:44]:<44} "
                         f"{row['bytes'] / _MB:>10.3f}  {row['class']}")
        relief = d.get("relief") or {}
        if relief.get("engaged"):
            lines.append(
                f"relief[{relief.get('mode')}]: peak "
                f"{relief.get('peak_before_bytes', 0) / _MB:.3f} -> "
                f"{relief.get('peak_after_bytes', 0) / _MB:.3f} MB, "
                f"saved {relief.get('bytes_saved', 0) / _MB:.3f} MB for "
                f"{relief.get('modeled_overhead_s', 0.0):.3e} s modeled")
            lines.append(f"{'Relief fixes':<44} {'MB saved':>10}  fix")
            for fx in relief.get("fixes", ()):
                lines.append(
                    f"{str(fx.get('var', ''))[:44]:<44} "
                    f"{fx.get('saved_bytes', 0) / _MB:>10.3f}  "
                    f"{fx.get('fix')}")
        return "\n".join(lines)


def _zero_shard_sets(program: Program, block: Block, ops, ndev: int,
                     stage: int, use_shard_map: bool):
    """(opt_sharded, sharded_params, grad_sharded, scatter_ops) from the
    SAME planning helpers the DP runtime uses — one source of truth for
    what shards at each ZeRO stage."""
    from ..parallel.data_parallel import (_pjit_zero23_sets,
                                          _plan_wrapped_updates,
                                          _sharded_opt_state)

    opt_sharded: set = set()
    sharded_params: set = set()
    grad_sharded: set = set()
    scatter_at: Dict[str, int] = {}  # grad name -> reduce-scatter op idx
    if stage < 1 or ndev <= 1:
        return opt_sharded, sharded_params, grad_sharded, scatter_at
    if use_shard_map:
        _, opt_sharded, sharded_params = _plan_wrapped_updates(
            ops, block, ndev, stage)
        if stage >= 2:
            for i, op_ in enumerate(ops):
                if op_.type == "c_fused_reduce_scatter":
                    for g in op_.inputs.get("X", []):
                        grad_sharded.add(g)
                        scatter_at[g] = i
    else:
        opt_sharded = _sharded_opt_state(ops, block, ndev)
        sharded_params, grad_constraints = _pjit_zero23_sets(
            ops, block, ndev, stage)
        for names in grad_constraints.values():
            grad_sharded.update(names)
    return opt_sharded, sharded_params, grad_sharded, scatter_at


def _tp_predicate(block: Block, tp: int, tp_rules: Optional[Dict]):
    """name -> True when the var holds 1/tp per device under tensor
    parallelism: it matches a ``tp_rules`` pattern (exact name or
    fullmatch regex — the same resolution ``apply_tensor_parallel``
    uses), or, with no rules given, it carries a ``shard_parameter``
    annotation (``var._sharding``)."""
    if tp <= 1:
        return lambda name: False
    if tp_rules:
        import re as _re

        pats = []
        for p in tp_rules:
            try:
                pats.append((p, _re.compile(p)))
            except _re.error:
                pats.append((p, None))

        def match(name: str) -> bool:
            for p, rx in pats:
                if name == p or (rx is not None and rx.fullmatch(name)):
                    return True
            return False

        return match

    def annotated(name: str) -> bool:
        v = block._find_var_recursive(name)
        return bool(getattr(v, "_sharding", None))

    return annotated


def plan_memory(program: Program, feed_names: Sequence[str] = (),
                fetch_names: Sequence[str] = (), *,
                ndev: int = 1, stage: Optional[int] = None,
                use_shard_map: Optional[bool] = None,
                donate: Optional[bool] = None,
                prefetch_records: Optional[Sequence[dict]] = None,
                prefetch_depth: Optional[int] = None,
                assumed_batch: int = 64,
                extra_resident: Optional[Dict[str, int]] = None,
                tp: int = 1,
                tp_rules: Optional[Dict] = None,
                scope=None) -> MemoryPlan:
    """Compute the modeled per-device HBM plan for ``program``.

    ``stage`` / ``prefetch_depth`` / ``donate`` default from the live
    flags (FLAGS_dp_sharding / FLAGS_dp_prefetch_depth /
    FLAGS_tpu_donate_buffers & FLAGS_tpu_step_session).
    ``prefetch_records`` takes precedence over re-deriving the ZeRO-3
    windows (pass ``compiled._prefetch_plan`` for the compiled truth).
    ``extra_resident`` adds named fixed blocks the program cannot see
    (e.g. an engine-held KV pool when planning the reference program).
    ``scope`` resolves the byte size of resident vars the program
    declares SHAPELESS (the serving K/V pools: persistable block vars
    whose real array lives only in the scope) — the compile paths pass
    their scope so those fixed blocks are charged at true size.

    ``tp`` (with ``tp_rules``, a name/regex -> spec dict like
    ``decoder_tp_rules``'s) prices tensor parallelism: a var matching a
    rule — or, with no rules given, carrying a ``_sharding``
    annotation — holds ``1/tp`` of its global bytes per device
    (weights, KV pools and scale pools shard; activations, block
    tables and the allocator stay replicated).  ``extra_resident``
    entries matching a rule divide too, so an engine-held pool priced
    from outside the program scales with the candidate degree.
    """
    from ..utils.flags import flag
    from ..parallel.data_parallel import _program_has_collectives

    if stage is None:
        stage = int(flag("dp_sharding") or 0)
    if donate is None:
        donate = bool(flag("tpu_donate_buffers", True)) and \
            bool(flag("tpu_step_session", True))
    if use_shard_map is None:
        use_shard_map = _program_has_collectives(program)
    ndev = max(int(ndev), 1)
    block = program.global_block()
    ops = list(block.ops)
    n_ops = max(len(ops), 1)
    feed_names = set(feed_names)
    fetch_names = set(fetch_names)

    opt_sharded, sharded_params, grad_sharded, scatter_at = \
        _zero_shard_sets(program, block, ops, ndev, stage, use_shard_map)

    tp = max(int(tp), 1)
    tp_sharded = _tp_predicate(block, tp, tp_rules)

    params = {p.name for p in program.all_parameters()}
    events = [op_reads_writes(op_) for op_ in ops]

    # ---- lifetime intervals ---------------------------------------------
    written: set = set()
    first_def: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    resident: set = set()       # live from op 0 (state / feeds)
    inplace_updated: set = set()  # resident names written in place
    for i, (rs, ws) in enumerate(events):
        for n in rs:
            if n == EMPTY:
                continue
            if n not in written and n not in feed_names:
                resident.add(n)
            last_use[n] = i
        # sub-block free reads keep the captured value live
        for sb in (v for v in ops[i].attrs.values() if isinstance(v, Block)):
            for sop in sb.ops:
                for n in sop.input_arg_names:
                    if n != EMPTY and n not in sb.vars:
                        if n not in written and n not in feed_names:
                            resident.add(n)
                        last_use[n] = i
        for n in ws:
            if n == EMPTY:
                continue
            if n in resident:
                inplace_updated.add(n)
            first_def.setdefault(n, i)
            written.add(n)
            last_use.setdefault(n, i)
    for n in feed_names:
        resident.add(n)
    # persistable writes and fetches live to the end of the step
    for n in list(written):
        v = block._find_var_recursive(n)
        if n in fetch_names or (v is not None
                                and getattr(v, "persistable", False)):
            last_use[n] = n_ops - 1

    def _scope_bytes(name: str) -> Optional[int]:
        if scope is None:
            return None
        try:
            v = scope.get(name)
        except Exception:
            return None
        nb = getattr(v, "nbytes", None)
        return int(nb) if nb else None

    def dev_bytes(name: str) -> Optional[int]:
        if name.endswith(HOST_STAGE_SUFFIX):
            # relief offload staging buffer: host RAM, not HBM
            return 0
        b = var_bytes(block, name, assumed_batch)
        v = block._find_var_recursive(name)
        if b is None or v is None or not v.shape:
            # undeclared or ()-shaped declaration: the scope value (the
            # compile-time ground truth — e.g. the serving K/V pools
            # declare shapeless and stage the real array) wins
            sb = _scope_bytes(name)
            if sb is not None:
                b = sb
        if b is None:
            return None
        if tp > 1 and tp_sharded(name):
            # tensor-parallel shard: weights / KV pools hold 1/tp of
            # the global bytes per device (scope arrays report the
            # GLOBAL logical nbytes under a NamedSharding, so the
            # division applies on that path too)
            b //= tp
        if ndev > 1:
            if name in sharded_params or name in opt_sharded \
                    or name in feed_names:
                # ZeRO-3 params / ZeRO-1 opt state resident 1/ndev;
                # feeds are batch-sharded over the dp axis
                return b // ndev
        return b

    classes: Dict[str, str] = {}
    per_var: Dict[str, dict] = {}
    diff = [0] * (n_ops + 1)

    def charge(name, lo, hi, nbytes):
        diff[lo] += nbytes
        diff[min(hi, n_ops - 1) + 1] -= nbytes

    resident_bytes = 0
    resident_by_class = {c: 0 for c in CLASSES}
    for n in sorted(resident | written | feed_names):
        if n.startswith("@"):
            continue
        b = dev_bytes(n)
        if not b:
            continue
        is_res = n in resident
        cls = _classify(n, params=params, opt_state=opt_sharded or set(),
                        feeds=feed_names, resident=is_res)
        # opt-state classification at stage 0: fall back to the slot
        # tables (opt_sharded is empty then)
        if cls == "state" and ("moment" in n.lower()
                               or "velocity" in n.lower()
                               or "_beta" in n.lower()
                               or "pow_acc" in n.lower()):
            cls = "opt_state"
        classes[n] = cls
        lo = 0 if is_res else first_def.get(n, 0)
        hi = last_use.get(n, lo)
        if is_res:
            hi = n_ops - 1  # state re-enters the scope after the step
        sharded_grad = (ndev > 1 and n in grad_sharded)
        b_full = var_bytes(block, n, assumed_batch) or b
        if sharded_grad and n in scatter_at:
            # shard_map ZeRO-2: full until the reduce-scatter, 1/ndev
            # after it (the steady-state per-dev grad-buffer bytes)
            charge(n, lo, scatter_at[n], b_full)
            if scatter_at[n] < hi:
                charge(n, scatter_at[n] + 1, hi, b_full // ndev)
            eff = b_full // ndev
        elif sharded_grad:
            # pjit ZeRO-2: GSPMD reduce-scatters at production — the
            # full gradient never materializes
            eff = b_full // ndev
            charge(n, lo, hi, eff)
        else:
            eff = b
            charge(n, lo, hi, eff)
        if is_res:
            resident_bytes += eff
            resident_by_class[cls] += eff
        # donation aliasing: with donation OFF, the in-place update's
        # result is a second buffer coexisting with the (scope-owned)
        # input copy until the post-step writeback
        if not donate and n in inplace_updated:
            charge(n, first_def.get(n, 0), n_ops - 1, eff)
        per_var[n] = {"bytes": int(b_full),
                      "dev_bytes": int(eff), "class": cls,
                      "first": lo, "last": hi, "resident": is_res,
                      "sharded": bool(sharded_grad
                                      or (tp > 1 and tp_sharded(n))
                                      or (ndev > 1
                                          and (n in sharded_params
                                               or n in opt_sharded)))}

    extra_resident = dict(extra_resident or {})
    if tp > 1:
        extra_resident = {k: (int(v) // tp if tp_sharded(k) else int(v))
                          for k, v in extra_resident.items()}
    extra_bytes = int(sum(extra_resident.values()))
    resident_bytes += extra_bytes
    if extra_bytes:
        resident_by_class["kv_pool"] += extra_bytes

    # ---- ZeRO-3 gather windows ------------------------------------------
    prefetch_windows = 0
    if ndev > 1 and stage >= 3 and sharded_params:
        if prefetch_records is None:
            if prefetch_depth is None:
                from ..utils.flags import flag as _flag

                prefetch_depth = int(_flag("dp_prefetch_depth") or 0)
            if prefetch_depth > 0:
                from ..parallel.data_parallel import _plan_param_prefetch

                prefetch_records, _, _ = _plan_param_prefetch(
                    ops, block, sharded_params, set(), prefetch_depth)
            else:
                prefetch_records = []
        if prefetch_records:
            for rec in prefetch_records:
                p = rec.get("param")
                b = var_bytes(block, p, assumed_batch)
                if not b:
                    continue
                bump = b - b // ndev  # full copy minus the resident shard
                charge(p, int(rec.get("gather_at", 0)),
                       int(rec.get("last_consumer", 0)), bump)
                prefetch_windows += 1
        else:
            # depth 0: just-in-time gather at every consumer op
            for p in sharded_params:
                b = var_bytes(block, p, assumed_batch)
                if not b:
                    continue
                bump = b - b // ndev
                for i, (rs, _) in enumerate(events):
                    if p in rs:
                        charge(p, i, i, bump)

    # ---- timeline + per-op transients -----------------------------------
    transients: List[dict] = []
    trans = [0] * n_ops
    for i, op_ in enumerate(ops):
        t = transient_bytes(op_, block, ndev, assumed_batch)
        if t:
            trans[i] = t
            transients.append({"op_index": i, "type": op_.type,
                               "bytes": int(t)})

    timeline: List[int] = []
    cur = extra_bytes
    peak = -1
    peak_i = 0
    for i in range(n_ops):
        cur += diff[i]
        total = cur + trans[i]
        timeline.append(int(total))
        if total > peak:
            peak, peak_i = total, i

    # ---- top live vars at the peak op -----------------------------------
    top: List[Tuple[str, int, str]] = []
    for n, info in per_var.items():
        if info["first"] <= peak_i <= info["last"]:
            top.append((n, info["dev_bytes"], info["class"]))
    for n, b in extra_resident.items():
        top.append((n, int(b), "kv_pool"))
    top.sort(key=lambda t: -t[1])

    return MemoryPlan(
        peak_bytes=int(max(peak, 0)), peak_op_index=peak_i,
        peak_op_type=(ops[peak_i].type if ops else "<empty>"),
        timeline=timeline, resident_bytes=int(resident_bytes),
        resident_by_class=resident_by_class, per_var=per_var,
        transients=transients, top_at_peak=top, ndev=ndev, stage=stage,
        donate=donate, path=("shard_map" if use_shard_map else "pjit"),
        assumed_batch=assumed_batch, n_ops=len(ops),
        extra_resident_bytes=extra_bytes,
        prefetch_windows=prefetch_windows)


def plan_and_surface(program: Program, where: str,
                     feed_names: Sequence[str] = (),
                     fetch_names: Sequence[str] = (), *,
                     block: Optional[Block] = None,
                     **plan_kw) -> Optional["MemoryPlan"]:
    """The compile-path entry both the executor and the DP runner call:
    build the plan, publish the ``hbm_modeled_peak_bytes{where=}``
    gauge, enforce FLAGS_hbm_budget_mb (:func:`check_budget` warns /
    raises per FLAGS_hbm_budget_strict), and emit the modeled timeline
    onto the profiler's memory lane when a session is live.
    Best-effort except for the budget gate: a planner bug must not take
    compilation down (logged at debug), but a configured budget
    violation MUST surface."""
    import logging

    try:
        plan = plan_memory(program, feed_names=feed_names,
                           fetch_names=fetch_names, **plan_kw)
    except Exception:
        logging.getLogger(__name__).debug(
            "memory planning failed for %s", where, exc_info=True)
        return None
    from ..utils import telemetry as tm

    tm.gauge("hbm_modeled_peak_bytes",
             "modeled per-device HBM peak of the last compilation "
             "(framework/memory_plan.py)",
             labels=("where",)).labels(where=where).set(plan.peak_bytes)
    # memory_relief_pass decisions (framework/ir.py): the compile
    # pipeline leaves its report on the program; the plan carries it to
    # compiled._memory_plan, the OOM debris dump, and the relief gauges
    relief = getattr(program, "_memory_relief", None)
    if relief is not None:
        plan.relief = relief
        if relief.get("engaged"):
            surface_relief(relief, where)
    b = budget_bytes()
    if b and plan.peak_bytes > b and plan.relief_candidates is None:
        # over budget with no relief applied: price the top candidate
        # fixes so the warning is actionable even with relief off
        try:
            from .ir import relief_candidate_summary

            plan.relief_candidates = relief_candidate_summary(
                program, plan, feed_names=feed_names,
                fetch_names=fetch_names)
        except Exception:
            plan.relief_candidates = []
    check_budget(plan, where)
    try:
        emit_trace_counters(plan, block if block is not None
                            else program.global_block())
    except Exception:
        pass
    return plan


# ==========================================================================
# budget gate (FLAGS_hbm_budget_mb)
# ==========================================================================
def budget_bytes() -> int:
    """The configured HBM budget in bytes (0 = unset/off)."""
    from ..utils.flags import flag

    try:
        mb = float(flag("hbm_budget_mb") or 0)
    except (TypeError, ValueError):
        return 0
    return int(mb * _MB) if mb > 0 else 0


def check_budget(plan: MemoryPlan, where: str = "compile",
                 strict: Optional[bool] = None) -> Optional[str]:
    """Enforce FLAGS_hbm_budget_mb against the modeled peak: returns
    None under budget; over budget, builds a message naming the peak op
    and the top-10 live vars, then warns (default) or raises
    :class:`MemoryBudgetError` (FLAGS_hbm_budget_strict).  Off (the
    default, budget 0) this is one flag read."""
    b = budget_bytes()
    if not b or plan is None or plan.peak_bytes <= b:
        return None
    from ..utils.flags import flag

    if strict is None:
        strict = bool(flag("hbm_budget_strict"))
    tops = ", ".join(f"{n}={v / _MB:.2f}MB[{c}]"
                     for n, v, c in plan.top_live_at_peak(10))
    msg = (f"[{where}] modeled HBM peak {plan.peak_mb:.2f} MB exceeds "
           f"FLAGS_hbm_budget_mb={b / _MB:g} at op "
           f"#{plan.peak_op_index} ({plan.peak_op_type}); top live vars: "
           f"{tops}")
    cands = getattr(plan, "relief_candidates", None)
    if cands:
        # priced by the memory_relief_pass machinery: what turning
        # FLAGS_memory_relief on would do, cheapest first
        fixes = ", ".join(
            f"{c['var']} {c['fix']} saves {c['saved_bytes'] / _MB:.2f}MB "
            f"@{c['seconds_per_byte']:.1e}s/B" for c in cands[:3])
        msg += (f"; candidate fixes (set FLAGS_memory_relief to apply): "
                f"{fixes}")
    if strict:
        raise MemoryBudgetError(msg)
    import warnings

    warnings.warn(msg, ResourceWarning, stacklevel=3)
    return msg


def surface_relief(report: dict, where: str) -> None:
    """Publish one relief report (memory_relief_pass.report) onto the
    hbm_relief_* gauges.  Best-effort: telemetry failure must not take
    compilation down."""
    try:
        from ..utils import telemetry as tm

        tm.gauge("hbm_relief_bytes_saved",
                 "modeled HBM bytes the memory_relief_pass bought back "
                 "at the last compilation",
                 labels=("where",)).labels(where=where).set(
            int(report.get("bytes_saved", 0)))
        tm.gauge("hbm_relief_modeled_overhead_s",
                 "modeled seconds/step the relief fixes spend "
                 "(recompute + exposed host transfer + plan delta)",
                 labels=("where",)).labels(where=where).set(
            float(report.get("modeled_overhead_s", 0.0)))
        counts: Dict[str, int] = {}
        for fx in report.get("fixes", ()):
            counts[fx.get("fix", "?")] = counts.get(fx.get("fix", "?"), 0) + 1
        g = tm.gauge("hbm_relief_vars",
                     "relieved vars by fix kind at the last compilation",
                     labels=("where", "fix"))
        for fix in ("remat", "offload", "plan"):
            g.labels(where=where, fix=fix).set(counts.get(fix, 0))
    except Exception:
        pass


# ==========================================================================
# chrome-trace memory lane (profiler counter events)
# ==========================================================================
def emit_trace_counters(plan: MemoryPlan, block: Optional[Block] = None,
                        name: str = "hbm_modeled_live_bytes") -> int:
    """Emit the modeled live-bytes timeline as chrome-trace counter
    ("C"-phase) events on the ``memory`` lane, spaced by the cost
    model's modeled per-op times so the lane's shape lines up with the
    modeled step.  No-op (returns 0) when the profiler is off."""
    from .. import profiler

    if not profiler.is_profiler_enabled() or not plan.timeline:
        return 0
    dt = None
    if block is not None:
        try:
            from ..utils.cost_model import CostModel, op_time_s

            cm = CostModel()
            dt = [op_time_s(op_, block, cm) for op_ in block.ops]
        except Exception:
            dt = None
    if not dt or len(dt) != len(plan.timeline):
        dt = [1e-6] * len(plan.timeline)
    budget = budget_bytes()
    t = time.perf_counter()
    n = 0
    for v, step in zip(plan.timeline, dt):
        args = {"bytes": int(v)}
        if budget:
            args["budget_bytes"] = int(budget)
        profiler.counter_event(name, args, cat="memory", ts=t)
        t += max(step, 1e-9)
        n += 1
    # close the lane at the resident floor so the counter doesn't dangle
    profiler.counter_event(name, {"bytes": int(plan.resident_bytes),
                                  **({"budget_bytes": int(budget)}
                                     if budget else {})},
                           cat="memory", ts=t)
    return n


# ==========================================================================
# OOM flight recorder (FLAGS_oom_debris_dir)
# ==========================================================================
#: allocator-OOM phrasings across the XLA/PJRT error surfaces.  No bare
#: "OOM" marker: it substring-matches unrelated messages ("ZOOM", a
#: user path) and a misfiled debris dump is a misleading post-mortem.
_RESOURCE_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory",
                     "Allocation failure")
_debris_lock = threading.Lock()
_debris_seq = 0


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when ``exc`` looks like a device allocator OOM (XLA raises
    ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...``; the markers also
    catch the PJRT C-API phrasings)."""
    s = f"{type(exc).__name__}: {exc}"
    return any(m in s for m in _RESOURCE_MARKERS)


def record_oom_debris(where: str, exc: BaseException,
                      plan: Optional[MemoryPlan] = None,
                      program: Optional[Program] = None,
                      extra: Optional[dict] = None) -> Optional[str]:
    """Dump a post-mortem debris directory for a device OOM: the
    modeled memory plan, a telemetry snapshot, the profiler's trace (if
    a session is live), measured device memory stats, and the error
    with traceback.  Returns the directory path, or None when
    ``FLAGS_oom_debris_dir`` is unset.  Never raises — the original
    exception must keep propagating unchanged."""
    from ..utils.flags import flag

    root = flag("oom_debris_dir") or ""
    if not root:
        return None
    global _debris_seq
    try:
        with _debris_lock:
            _debris_seq += 1
            seq = _debris_seq
        d = os.path.join(str(root),
                         f"oom_{where}_{os.getpid()}_{seq}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "error.txt"), "w") as f:
            f.write(f"where: {where}\n")
            f.write(f"type: {type(exc).__name__}\n")
            f.write(f"error: {exc}\n\n")
            f.write("".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__)))
        if plan is not None:
            with open(os.path.join(d, "plan.json"), "w") as f:
                json.dump({**plan.as_dict(20),
                           "timeline_bytes": plan.timeline}, f, indent=2)
        try:
            from ..utils import telemetry

            with open(os.path.join(d, "telemetry.json"), "w") as f:
                json.dump(telemetry.snapshot(), f, indent=2)
        except Exception:
            pass
        try:
            from .. import profiler

            events = profiler.get_events()
            if events:
                profiler._write_chrome_trace(
                    events, os.path.join(d, "trace.json"))
        except Exception:
            pass
        try:
            from ..utils.memory import memory_stats

            with open(os.path.join(d, "memory_stats.json"), "w") as f:
                json.dump(memory_stats(0), f, indent=2)
        except Exception:
            pass
        if program is not None:
            try:
                counts: Dict[str, int] = {}
                for blk in program.blocks:
                    for op_ in blk.ops:
                        counts[op_.type] = counts.get(op_.type, 0) + 1
                with open(os.path.join(d, "program.json"), "w") as f:
                    json.dump({"n_blocks": len(program.blocks),
                               "op_counts": dict(sorted(counts.items()))},
                              f, indent=2)
            except Exception:
                pass
        if extra:
            with open(os.path.join(d, "context.json"), "w") as f:
                json.dump(extra, f, indent=2, default=str)
        import logging

        logging.getLogger(__name__).error(
            "RESOURCE_EXHAUSTED in %s — debris dumped to %s", where, d)
        return d
    except Exception:
        return None
