"""Static SPMD shard-safety analysis: one abstract interpreter over
Program/Block that every compiled program form shares.

Until r26 the repo had two unrelated static guards over its growing set
of per-device programs: the r10 verifier's flat ``check_collective_order``
fingerprint and the r20 numerics probe's private shard-variance taint
walk (``NumericsProbePass._shard_variant_names``).  Every upcoming rung
on ROADMAP directions 2/3 — pipeline-bubble plan axes, per-bucket wire
compression, hierarchical ICI x DCN collectives, elastic
shrink-and-continue — multiplies the number of distinct programs whose
collectives must agree, so this module builds the checker ONCE as a
first-class analysis ("End-to-end Adaptive Distributed Training on
PaddlePaddle", arXiv:2112.02752, validates derived parallel plans before
execution; EQuARX, arXiv:2506.17615, previews mixed-precision
collectives whose dtype/ring mismatches are exactly the bug class a
static checker catches).

**Distribution-state lattice.**  Each var name carries one of three
states, ordered ``replicated < sharded < variant``:

* ``replicated`` — provably the same value on every device (parameters,
  counters, the output of a replicating collective);
* ``sharded``    — a deterministic 1/ndev shard of a global value
  (reduce-scattered grads, ZeRO-sharded optimizer state, ``c_split``
  outputs, tensor-parallel annotated weights);
* ``variant``    — arbitrary per-device divergence (batch-sharded
  feeds, RNG-derived values, anything computed from either).

States are seeded from feeds (read-before-write non-persistable names),
RNG/stateful ops, partition-rule specs (``_sharding`` annotations) and
ZeRO-sharded state (``data_parallel._plan_wrapped_updates``), then
propagated forward through op read/write sets: replicating collectives
(:data:`REPLICATING_COLLECTIVES`) clear to ``replicated``, scattering
ones (:data:`SHARDING_COLLECTIVES`) set ``sharded``, wrapped shard
updates gather their ParamOut back while their state slots stay
shard-resident, and everything else joins its inputs.  The
``variant_names`` view of the final states is the exact r20 taint walk
(parity pinned by tests/test_shard_analysis.py), and
``framework/ir.py numerics_probe_pass`` consumes it — the old private
walk is deleted.

**Checks** (each finding carries op index, var name and the inferred
state chain):

1. :func:`check_replication_soundness` — a var consumed where a
   replicated value is required (update-op replicated slots per
   ``partition_rules.REPLICATED_SLOT_RULES`` + LearningRate, host-op
   reads, the numerics probe's packed stats vector) must be provably
   replicated at that read;
2. :func:`check_collective_context` — collectives under a shard-variant
   branch predicate or inside a loop body whose trip count can diverge
   per device (the classic SPMD deadlock), found by descending into
   cond / while / while_loop sub-blocks;
3. :func:`check_comm_hazards` — an in-place write must not clobber a
   buffer a still-outstanding overlapped collective reads (the r9
   overlap schedule issues bucket collectives early; XLA's async
   collectives are in flight until the first consumer), and r16
   prefetch gather windows must not cross a write to their param;
4. :func:`check_member_programs` — cross-program agreement over the
   verifier's EXTENDED collective signature (ring, reduce-op, dtype,
   sharded payload shape; sub-block descent) for tp/dp member sets,
   reusable offline via ``tools/progcheck.py --shard``.

The :func:`gate` entry is flag-guarded (``FLAGS_shard_safety``, default
ON as warn; ``FLAGS_shard_safety_strict`` raises ``VerifyError``) and
analysis-only: it never mutates a program, so defaults are
bit-identical.  Programs without collectives short-circuit to zero
findings — single-device programs have no SPMD obligations.
"""
from __future__ import annotations

import re
import warnings
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .core import Block, Operator, Program
from .verifier import (Diagnostic, SEV_ERROR, SEV_WARNING, VerifyError,
                       _LOCAL_SYNC_OPS, _sub_block_attrs, EMPTY)

__all__ = [
    "REPLICATED", "SHARDED", "VARIANT", "DistState", "ShardAnalysis",
    "REPLICATING_COLLECTIVES", "SHARDING_COLLECTIVES", "analyze",
    "variant_names", "check_replication_soundness",
    "check_collective_context", "check_comm_hazards", "check_program",
    "check_member_programs", "gate", "enabled", "strict",
]

REPLICATED = "replicated"
SHARDED = "sharded"
VARIANT = "variant"

_RANK = {REPLICATED: 0, SHARDED: 1, VARIANT: 2}

#: collective ops whose output is replicated across shards — they CLEAR
#: shard-variance (the r20 walk's _CLEARS set, now shared)
REPLICATING_COLLECTIVES = frozenset({
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "allreduce", "c_fused_allreduce",
    "c_allgather", "c_broadcast", "broadcast",
})
#: collective ops whose output is a per-device shard — they SET it
#: (the r20 walk's _SHARDS set, now shared)
SHARDING_COLLECTIVES = frozenset({
    "c_fused_reduce_scatter", "c_reducescatter", "c_split", "alltoall",
})

#: control-flow ops whose sub-blocks the context check descends into
_CONTROL_OPS = frozenset({"cond", "while", "while_loop", "recurrent"})

_CHAIN_CAP = 6  # provenance entries kept per state (head ... tail)


def _is_collective(op_type: str) -> bool:
    if op_type in _LOCAL_SYNC_OPS:
        return False
    return (op_type.startswith("c_")
            or op_type in ("allreduce", "broadcast", "barrier"))


class DistState:
    """One var's distribution state plus its provenance chain."""

    __slots__ = ("kind", "axis", "chain")

    def __init__(self, kind: str, axis=None, chain: Tuple[str, ...] = ()):
        self.kind = kind
        self.axis = axis
        self.chain = chain

    @property
    def replicated(self) -> bool:
        return self.kind == REPLICATED

    def extend(self, note: str) -> "DistState":
        chain = self.chain + (note,)
        if len(chain) > _CHAIN_CAP:
            chain = chain[:1] + ("...",) + chain[-(_CHAIN_CAP - 2):]
        return DistState(self.kind, self.axis, chain)

    def describe(self) -> str:
        where = self.kind if self.axis is None \
            else f"{self.kind}[{self.axis}]"
        if not self.chain:
            return where
        return f"{where} ({' -> '.join(self.chain)})"

    def __repr__(self):
        return f"<DistState {self.describe()}>"


_REPL = DistState(REPLICATED)


def _join(states: Sequence[DistState]) -> DistState:
    best = _REPL
    for s in states:
        if _RANK[s.kind] > _RANK[best.kind]:
            best = s
    return best


def _zero_plan(ops, block):
    """(wrapped-update plans, ZeRO-sharded state names) for the current
    FLAGS_dp_sharding / mesh config — the same derivation the DP
    runner's shard_map path uses, so the two can never drift."""
    from ..utils.flags import flag

    stage = int(flag("dp_sharding") or 0)
    try:
        from ..parallel.mesh import ring_axis_size

        ndev = int(ring_axis_size(0))
    except Exception:
        ndev = 1
    if stage < 1 or ndev <= 1:
        return {}, set(), stage
    from ..parallel.data_parallel import _plan_wrapped_updates

    plans, sharded_state, _ = _plan_wrapped_updates(ops, block, ndev, stage)
    return plans, sharded_state, stage


def _shard_annotations(block) -> Dict[str, object]:
    """Vars carrying a partition-rule / tensor-parallel ``_sharding``
    spec that names at least one mesh axis (tensor_parallel helpers)."""
    from ..parallel.tensor_parallel import annotated_shard_axes

    return annotated_shard_axes(block)


class ShardAnalysis:
    """Forward abstract interpretation of one block's op list.

    ``states`` holds the FINAL per-name states after the walk;
    flow-sensitive consumers (the replication-soundness check) pass an
    ``on_op(i, op_, states)`` observer, called before each op's write
    effects apply — i.e. with the states its reads observe."""

    def __init__(self, program: Program, block: Optional[Block] = None):
        self.program = program
        self.block = block if block is not None \
            else program.global_block()
        self.states: Dict[str, DistState] = {}
        self.plans: Dict[int, dict] = {}
        self.stage = 0

    # -- seeding -----------------------------------------------------------
    def seed(self) -> "ShardAnalysis":
        from ..ops import registry as _registry

        block = self.block
        ops = list(block.ops)
        self.plans, sharded_state, self.stage = _zero_plan(ops, block)

        written: set = set()
        for op_ in ops:
            for n in op_.input_arg_names:
                if n in written or n == EMPTY or n in self.states:
                    continue
                var = block._find_var_recursive(n)
                if var is None or not getattr(var, "persistable", False):
                    self.states[n] = DistState(
                        VARIANT, chain=(
                            f"seed: {n!r} feed-like (read before write, "
                            f"non-persistable)",))
            written.update(op_.output_arg_names)

        for n in sharded_state:
            self.states[n] = DistState(
                SHARDED, axis="dp", chain=(
                    f"seed: {n!r} ZeRO-sharded optimizer state "
                    f"(stage {self.stage})",))
        for n, axes in _shard_annotations(block).items():
            if n not in self.states:
                self.states[n] = DistState(
                    SHARDED, axis=next(a for a in axes if a is not None),
                    chain=(f"seed: {n!r} partition-rule spec {axes!r}",))
        self._registry = _registry
        return self

    # -- propagation -------------------------------------------------------
    def _ring_axis(self, op_) -> object:
        ring = op_.attrs.get("ring_id", 0)
        try:
            from ..parallel.mesh import registry as _mesh_registry

            axis = _mesh_registry().axis_for_ring(ring)
        except Exception:
            axis = None
        return axis if axis is not None else f"ring{ring}"

    def propagate(self, on_op: Optional[Callable] = None
                  ) -> "ShardAnalysis":
        states = self.states
        for i, op_ in enumerate(self.block.ops):
            if on_op is not None:
                on_op(i, op_, states)
            outs = [n for n in op_.output_arg_names if n != EMPTY]
            plan = self.plans.get(id(op_))
            if plan is not None:
                # wrapped shard update: ParamOut gathers back to full
                # width (or stays a shard every consumer auto-gathers);
                # state-slot outputs stay shard-resident
                for n in outs:
                    if n == plan["param"]:
                        states.pop(n, None)
                    else:
                        states[n] = DistState(
                            SHARDED, axis="dp",
                            chain=(f"op #{i} ({op_.type}) shard-wrapped "
                                   f"update writes {n!r}",))
                continue
            if op_.type in REPLICATING_COLLECTIVES:
                for n in outs:
                    states.pop(n, None)
                continue
            if op_.type in SHARDING_COLLECTIVES:
                axis = self._ring_axis(op_)
                for n in outs:
                    states[n] = DistState(
                        SHARDED, axis=axis,
                        chain=(f"op #{i} ({op_.type}) scatters {n!r}",))
                continue
            d = self._registry.OPS.get(op_.type)
            stateful = d is not None and d.stateful
            if stateful:
                for n in outs:
                    states[n] = DistState(
                        VARIANT, chain=(
                            f"op #{i} ({op_.type}) is stateful/RNG — "
                            f"per-device stream",))
                continue
            src = _join([states[n] for n in op_.input_arg_names
                         if n in states])
            if src.replicated:
                for n in outs:
                    states.pop(n, None)
            else:
                carried = src.extend(f"op #{i} ({op_.type})")
                for n in outs:
                    states[n] = carried
        return self

    # -- views -------------------------------------------------------------
    def state_of(self, name: str) -> DistState:
        return self.states.get(name, _REPL)

    def variant_names(self) -> set:
        """Names whose runtime value differs per device — the exact
        contract of the r20 numerics taint walk (sharded counts: a
        shard IS a per-device-different value)."""
        return set(self.states)


def analyze(program: Program, block: Optional[Block] = None,
            on_op: Optional[Callable] = None) -> ShardAnalysis:
    return ShardAnalysis(program, block).seed().propagate(on_op=on_op)


def variant_names(program: Program, block: Optional[Block] = None) -> set:
    """Shard-variant names of ``block`` (default: global block) — the
    shared engine behind ``numerics_probe_pass``'s cross-shard stat
    combines."""
    return analyze(program, block).variant_names()


# ==========================================================================
# check 1: replication soundness
# ==========================================================================
def _replicated_slots(op_) -> List[str]:
    from ..parallel.partition_rules import (REPLICATED_SLOT_RULES,
                                            is_update_op)

    if not is_update_op(op_.type):
        return []
    slots = [s for s in op_.inputs
             if s == "LearningRate"
             or any(re.search(p, s) for p in REPLICATED_SLOT_RULES)]
    return slots


def _replication_observer(block, diags: List[Diagnostic]) -> Callable:
    """The per-op half of replication soundness, as an ``analyze``
    observer so callers can piggyback it on a walk they already pay
    for (``check_program`` shares ONE walk across checks 1 and 2)."""
    from ..ops import registry as _registry

    def on_op(i, op_, states):
        for slot in _replicated_slots(op_):
            for n in op_.inputs.get(slot, []):
                st = states.get(n)
                if st is None or n == EMPTY:
                    continue
                diags.append(Diagnostic(
                    SEV_ERROR, "replication-required",
                    f"update op consumes {n!r} in replicated slot "
                    f"{slot!r}, but it is {st.describe()} — the slot's "
                    f"math assumes one global value per device",
                    block.idx, i, op_.type, var=n,
                    pass_name="shard_safety"))
        d = _registry.OPS.get(op_.type)
        if d is not None and d.host and op_.type not in _CONTROL_OPS \
                and op_.type not in _LOCAL_SYNC_OPS:
            for n in op_.input_arg_names:
                st = states.get(n)
                if st is None or st.kind != VARIANT or n == EMPTY:
                    continue
                diags.append(Diagnostic(
                    SEV_ERROR, "replication-required",
                    f"host op reads {n!r}, which is {st.describe()} — "
                    f"a host read has no defined value when shards "
                    f"diverge", block.idx, i, op_.type, var=n,
                    pass_name="shard_safety"))

    return on_op


def _stats_var_diags(analysis: ShardAnalysis, block) -> List[Diagnostic]:
    """Post-walk half of replication soundness: the numerics probe's
    packed stats vector must end the program replicated."""
    from . import numerics as _numerics

    diags: List[Diagnostic] = []
    if block.has_var(_numerics.STATS_VAR):
        st = analysis.state_of(_numerics.STATS_VAR)
        if not st.replicated:
            diags.append(Diagnostic(
                SEV_ERROR, "replication-required",
                f"numerics stats vector {_numerics.STATS_VAR!r} is "
                f"{st.describe()} — probe partials of a shard-variant "
                f"var were not cross-shard combined",
                block.idx, var=_numerics.STATS_VAR,
                pass_name="shard_safety"))
    return diags


def check_replication_soundness(program: Program,
                                fetch_names: Sequence[str] = (),
                                ) -> List[Diagnostic]:
    """Vars consumed where a replicated value is required must be
    provably replicated: update-op replicated slots (beta-pow scalar
    accumulators, the learning rate), host-op reads (a host value is
    materialized once — divergent shards have no defined host value),
    and the numerics probe's packed stats vector (the probe stream
    treats row 0 as THE value)."""
    diags: List[Diagnostic] = []
    block = program.global_block()
    res = analyze(program, block, on_op=_replication_observer(block, diags))
    diags.extend(_stats_var_diags(res, block))
    return diags


# ==========================================================================
# check 2: collectives under divergent control flow (SPMD deadlock)
# ==========================================================================
def _sub_collectives(blocks, _seen=None) -> List[str]:
    """Recursively collect collective op types inside sub-blocks."""
    out: List[str] = []
    seen = _seen if _seen is not None else set()
    for blk in blocks:
        if id(blk) in seen:
            continue
        seen.add(id(blk))
        for op_ in blk.ops:
            if _is_collective(op_.type):
                out.append(op_.type)
            out.extend(_sub_collectives(_sub_block_attrs(op_), seen))
    return out


def _predicate_state(op_, analysis: ShardAnalysis) -> DistState:
    """Joined state of every value the control decision depends on:
    the Cond input plus — for while_loop, whose predicate is computed
    by its cond block — the carries and the cond block's free reads."""
    names = list(op_.inputs.get("Cond", []))
    if op_.type == "while_loop":
        names.extend(op_.input_arg_names)
        for sb in _sub_block_attrs(op_):
            for sop in sb.ops:
                names.extend(n for n in sop.input_arg_names
                             if n not in sb.vars)
    return _join([analysis.state_of(n) for n in set(names)
                  if n != EMPTY])


def check_collective_context(program: Program,
                             analysis: Optional[ShardAnalysis] = None,
                             ) -> List[Diagnostic]:
    """A collective under a shard-variant predicate deadlocks: devices
    whose predicate (or trip count) diverges issue different collective
    sequences and block each other forever.  Replicated predicates are
    fine (every device takes the same path), and divergent control flow
    WITHOUT collectives is legal SPMD — only the combination flags.
    Pass ``analysis`` to reuse an already-computed walk."""
    diags: List[Diagnostic] = []
    if analysis is None:
        analysis = analyze(program)
    block = program.global_block()
    for i, op_ in enumerate(block.ops):
        if op_.type not in _CONTROL_OPS:
            continue
        subs = _sub_block_attrs(op_)
        if not subs:
            continue
        inner = _sub_collectives(subs)
        if not inner:
            continue
        pred = _predicate_state(op_, analysis)
        if pred.replicated:
            continue
        loopish = op_.type != "cond"
        code = ("divergent-trip-count" if loopish
                else "collective-under-variant-predicate")
        what = ("per-device trip counts can diverge" if loopish
                else "devices can take different branches")
        diags.append(Diagnostic(
            SEV_ERROR, code,
            f"{op_.type!r} predicate is {pred.describe()} and its "
            f"sub-block issues collective(s) {sorted(set(inner))} — "
            f"{what}, so the collective sequences desynchronize "
            f"(SPMD deadlock)", block.idx, i, op_.type,
            var=(op_.inputs.get("Cond") or [None])[0],
            pass_name="shard_safety"))
    return diags


# ==========================================================================
# check 3: comm/compute hazard (overlap + prefetch windows)
# ==========================================================================
def check_comm_hazards(program: Program,
                       prefetch_records: Sequence[dict] = (),
                       ) -> List[Diagnostic]:
    """An overlapped collective is outstanding from its issue point to
    the first read of its result (XLA async collectives; the r9 overlap
    schedule deliberately issues bucket collectives early).  An op that
    WRITES the payload buffer inside that window — an in-place update,
    a donation-reusing rewrite — races the DMA.  The first READ closes
    the window (in-place read+write consumers observe the reduced value
    first).  The r16 prefetch gather windows are the same hazard for
    the runtime all-gathers: delegated to the verifier's window rule."""
    diags: List[Diagnostic] = []
    block = program.global_block()
    ops = list(block.ops)
    for i, op_ in enumerate(ops):
        if not _is_collective(op_.type) or op_.type == "barrier":
            continue
        payload = [n for n in op_.output_arg_names if n != EMPTY]
        for x in payload:
            for j in range(i + 1, len(ops)):
                nxt = ops[j]
                if x in nxt.input_arg_names:
                    break  # first consumer: the collective is awaited
                if x in nxt.output_arg_names:
                    diags.append(Diagnostic(
                        SEV_ERROR, "comm-compute-hazard",
                        f"op #{j} ({nxt.type}) writes {x!r} while the "
                        f"collective issued at op #{i} ({op_.type}) is "
                        f"still outstanding (no read between them) — "
                        f"the write races the in-flight transfer",
                        block.idx, j, nxt.type, var=x,
                        pass_name="shard_safety"))
                    break
    if prefetch_records:
        from .verifier import check_prefetch_plan

        for d in check_prefetch_plan(ops, block, prefetch_records):
            d.pass_name = "shard_safety"
            diags.append(d)
    return diags


# ==========================================================================
# check 4: cross-program (tp/dp member) agreement
# ==========================================================================
def check_member_programs(programs: Sequence[Program],
                          labels: Optional[Sequence[str]] = None,
                          ) -> List[Diagnostic]:
    """Every member of a tp/dp program set must issue the same
    collectives in the same order with the same (ring, reduce-op,
    dtype, payload shape) — the verifier's EXTENDED signature, so a
    dtype or reduce-op divergence is as fatal as a reorder."""
    from . import verifier

    diags = list(verifier.check_collective_order(programs))
    for d in diags:
        d.pass_name = d.pass_name or "shard_safety"
    return diags


# ==========================================================================
# program-level driver + flag-guarded gate
# ==========================================================================
def check_program(program: Program, feed_names: Sequence[str] = (),
                  fetch_names: Sequence[str] = (),
                  prefetch_records: Sequence[dict] = (),
                  ) -> List[Diagnostic]:
    """All single-program shard-safety checks.  Programs without
    collectives short-circuit: they carry no SPMD obligations, so the
    zoo of single-device programs yields zero findings by
    construction."""
    from ..parallel.data_parallel import _program_has_collectives

    if not _program_has_collectives(program):
        return []
    block = program.global_block()
    diags: List[Diagnostic] = []
    # ONE abstract-interpretation walk shared by checks 1 and 2: the
    # replication observer fires per op, the same final state feeds the
    # control-flow check and the stats-vector contract.
    analysis = analyze(program, block,
                       on_op=_replication_observer(block, diags))
    diags.extend(_stats_var_diags(analysis, block))
    diags.extend(check_collective_context(program, analysis=analysis))
    diags.extend(check_comm_hazards(program, prefetch_records))
    return diags


def enabled() -> bool:
    from ..utils.flags import flag

    return bool(flag("shard_safety"))


def strict() -> bool:
    from ..utils.flags import flag

    return bool(flag("shard_safety_strict"))


def gate(program: Program, feed_names: Sequence[str] = (),
         fetch_names: Sequence[str] = (),
         prefetch_records: Sequence[dict] = (),
         where: str = "shard_safety") -> List[Diagnostic]:
    """The compile-pipeline gate: run every check, WARN by default
    (``FLAGS_shard_safety``; analysis only — the program is never
    touched), raise ``VerifyError`` under ``FLAGS_shard_safety_strict``.
    Returns the findings either way so callers can attach a report."""
    if not enabled():
        return []
    diags = check_program(program, feed_names, fetch_names,
                          prefetch_records)
    errors = [d for d in diags if d.severity == SEV_ERROR]
    if errors and strict():
        raise VerifyError(errors, where)
    for d in diags:
        warnings.warn(f"[{where}] {d.format()}", RuntimeWarning,
                      stacklevel=2)
    return diags
