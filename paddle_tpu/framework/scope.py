"""Scope: name -> runtime value map with parent chaining.

Reference: paddle/fluid/framework/scope.h:46 (Scope) and variable.h:26
(Variable as an any-typed slot).  Here a scope slot holds either a
``jax.Array``, a numpy array, a LoDTensor wrapper, or arbitrary Python
objects (reader handles, etc.).  TPU-first: values are device arrays managed
by JAX; the executor moves them with ``jax.device_put`` as needed.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


class LoDTensor:
    """Tensor + level-of-detail ragged offsets (reference: lod_tensor.h:104).

    On TPU, ragged sequence batches are represented padded+masked for XLA;
    the LoD offsets ride along host-side so sequence ops can recover segment
    boundaries (SURVEY.md §7 hard-part 1)."""

    def __init__(self, value=None, lod: Optional[List[List[int]]] = None):
        self._value = value
        self._lod = lod or []

    def set(self, array, place=None):
        self._value = np.asarray(array)
        # the tensor may live in a scope slot (find_var().get_tensor()
        # .set(...) is the reference feed/init idiom): invalidate any
        # executor step session holding device-resident copies
        Scope.mutation_counter += 1

    def set_lod(self, lod):
        self._lod = lod

    def lod(self):
        return self._lod

    def recursive_sequence_lengths(self):
        return [
            [off[i + 1] - off[i] for i in range(len(off) - 1)] for off in self._lod
        ]

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = []
        for lens in lengths:
            off = [0]
            for l in lens:
                off.append(off[-1] + l)
            self._lod.append(off)

    def value(self):
        return self._value

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    def numpy(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Scope:
    #: process-wide write stamp: every value mutation of ANY scope bumps
    #: it.  The executor's step session (executor._StateSession) records
    #: the stamp after its own post-step writeback; a mismatch next step
    #: means someone else wrote a scope (checkpoint load, manual set,
    #: another executor) and the device-resident state must be re-read.
    #: Process-wide (not per-scope) because Scope.set writes through the
    #: parent chain — a parent-scope write must invalidate sessions
    #: holding a child scope.
    mutation_counter: int = 0

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self._parent = parent
        self._kids: List["Scope"] = []
        self._lock = threading.RLock()

    # reference API: Scope::Var / FindVar / LocalVar ------------------------
    def var(self, name: str) -> "_ScopeSlot":
        with self._lock:
            if name not in self._vars:
                self._vars[name] = None
                Scope.mutation_counter += 1
        return _ScopeSlot(self, name)

    def find_var(self, name: str) -> Optional["_ScopeSlot"]:
        s = self
        while s is not None:
            if name in s._vars:
                return _ScopeSlot(s, name)
            s = s._parent
        return None

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    def local_var_names(self) -> List[str]:
        return list(self._vars.keys())

    # value-level convenience (the executor's fast path) --------------------
    def get(self, name: str, default=None):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return default

    def has(self, name: str) -> bool:
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s._parent
        return False

    def set(self, name: str, value):
        # write where the name already lives (parent-chain), else locally
        Scope.mutation_counter += 1
        s = self
        while s is not None:
            if name in s._vars:
                s._vars[name] = value
                return
            s = s._parent
        self._vars[name] = value

    def erase(self, names):
        Scope.mutation_counter += 1
        for n in names:
            self._vars.pop(n, None)

    def items(self) -> Iterator:
        return iter(self._vars.items())


class _ScopeSlot:
    """Handle mirroring the reference's Variable* returned by Scope::Var."""

    def __init__(self, scope: Scope, name: str):
        self._scope = scope
        self._name = name

    def get_tensor(self) -> LoDTensor:
        v = self._scope.get(self._name)
        if not isinstance(v, LoDTensor):
            v = LoDTensor(v)
            self._scope._vars[self._name] = v
            Scope.mutation_counter += 1
        return v

    def get(self):
        return self._scope.get(self._name)

    def set(self, value):
        self._scope.set(self._name, value)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev
