"""SelectedRows: sparse row-set gradient value.

Capability parity with the reference SelectedRows runtime type
(reference: paddle/fluid/framework/selected_rows.h:32 — a {rows, value,
height} triple produced by sparse embedding backward and consumed by the
optimizers' SelectedRows kernels, operators/optimizers/*).

TPU-native design: SelectedRows is a jax pytree, so it flows through the
whole-program jit like any other value.  The embedding grad emits
(rows=flattened ids, values=out-grad rows) in O(batch) instead of a
dense O(vocab) scatter; sparse-aware optimizer lowerings then update
only the touched rows with ``param.at[rows].add`` (XLA scatter-add,
duplicate ids accumulate correctly).  Ops that are not sparse-aware see
a dense array via ``maybe_dense`` so correctness never depends on op
coverage.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    """rows: (n,) int32 row indices (duplicates allowed);
    values: (n, *dim) per-row values; height: static row count of the
    dense equivalent (selected_rows.h height_)."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = int(height)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, values = children
        return cls(rows, values, height)

    # -- conversions -------------------------------------------------------
    @property
    def dense_shape(self):
        return (self.height,) + tuple(jnp.shape(self.values)[1:])

    def to_dense(self):
        """Densify: O(height) memory — the fallback for non-sparse-aware
        consumers (reference: math::SelectedRowsToTensor)."""
        dense = jnp.zeros(self.dense_shape, self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def merge_rows(self):
        """Deduplicate rows by summing their values (reference:
        math::scatter::MergeAdd).  XLA needs static shapes, so the
        result keeps length n: each distinct row appears once with the
        summed value, and the leftover slots carry the sentinel row
        ``height`` — consumers must scatter with mode='drop' so the
        sentinel rows vanish.  Required before any read-modify-write
        optimizer update (momentum/adam/adagrad), where duplicate rows
        in a plain scatter would read stale state."""
        n = self.rows.shape[0]
        order = jnp.argsort(self.rows)
        r_s = jnp.take(self.rows, order)
        v_s = jnp.take(self.values, order, axis=0)
        boundary = jnp.concatenate(
            [jnp.ones((1,), jnp.int32),
             (r_s[1:] != r_s[:-1]).astype(jnp.int32)])
        seg = jnp.cumsum(boundary) - 1  # segment id per sorted position
        merged = jax.ops.segment_sum(v_s, seg, num_segments=n)
        rows_m = jnp.full((n,), self.height, r_s.dtype)
        rows_m = rows_m.at[seg].min(r_s)
        return SelectedRows(rows_m, merged, self.height)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.height,
            )
        # dense + sparse -> dense
        return maybe_dense(other) + self.to_dense()

    __radd__ = __add__

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype), self.height)

    @property
    def dtype(self):
        return self.values.dtype

    def numpy(self):
        return np.asarray(self.to_dense())


def maybe_dense(v):
    """Densify SelectedRows, pass anything else through."""
    return v.to_dense() if isinstance(v, SelectedRows) else v
