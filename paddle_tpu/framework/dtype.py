"""Dtype / VarType model.

Mirrors the capability of the reference's ``VarType`` proto enum
(reference: paddle/fluid/framework/framework.proto:103-136) but is a plain
Python enum with numpy/jax interop.  TPU-first: bfloat16 is a first-class
dtype (the reference's fp16 AMP maps to bf16 here by default).
"""
from __future__ import annotations

import enum

import numpy as np

try:  # jax.numpy provides bfloat16 via ml_dtypes
    import ml_dtypes

    bfloat16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    import jax.numpy as jnp

    bfloat16 = np.dtype(jnp.bfloat16)


class VarType(enum.IntEnum):
    # Tensor element dtypes (values follow the reference proto enum where
    # they exist: framework.proto:107-125).
    BOOL = 0
    INT16 = 1
    INT32 = 2
    INT64 = 3
    FP16 = 4
    FP32 = 5
    FP64 = 6
    UINT8 = 20
    INT8 = 21
    BF16 = 22
    COMPLEX64 = 23
    COMPLEX128 = 24

    # Variable container types (framework.proto:126-145).
    LOD_TENSOR = 7
    SELECTED_ROWS = 8
    FEED_MINIBATCH = 9
    FETCH_LIST = 10
    STEP_SCOPES = 11
    LOD_RANK_TABLE = 12
    LOD_TENSOR_ARRAY = 13
    PLACE_LIST = 14
    READER = 15
    RAW = 17
    TUPLE = 18


_NP_TO_VT = {
    np.dtype(np.bool_): VarType.BOOL,
    np.dtype(np.int16): VarType.INT16,
    np.dtype(np.int32): VarType.INT32,
    np.dtype(np.int64): VarType.INT64,
    np.dtype(np.float16): VarType.FP16,
    np.dtype(np.float32): VarType.FP32,
    np.dtype(np.float64): VarType.FP64,
    np.dtype(np.uint8): VarType.UINT8,
    np.dtype(np.int8): VarType.INT8,
    bfloat16: VarType.BF16,
    np.dtype(np.complex64): VarType.COMPLEX64,
    np.dtype(np.complex128): VarType.COMPLEX128,
}
_VT_TO_NP = {v: k for k, v in _NP_TO_VT.items()}

_STR_TO_VT = {
    "bool": VarType.BOOL,
    "int16": VarType.INT16,
    "int32": VarType.INT32,
    "int64": VarType.INT64,
    "float16": VarType.FP16,
    "float32": VarType.FP32,
    "float64": VarType.FP64,
    "uint8": VarType.UINT8,
    "int8": VarType.INT8,
    "bfloat16": VarType.BF16,
    "complex64": VarType.COMPLEX64,
    "complex128": VarType.COMPLEX128,
}
_VT_TO_STR = {v: k for k, v in _STR_TO_VT.items()}

FLOAT_TYPES = frozenset(
    {VarType.FP16, VarType.FP32, VarType.FP64, VarType.BF16}
)


def convert_dtype(dtype) -> VarType:
    """Accept VarType / numpy dtype / str / python type and return VarType."""
    if isinstance(dtype, VarType):
        return dtype
    if isinstance(dtype, str):
        try:
            return _STR_TO_VT[dtype]
        except KeyError:
            raise ValueError(f"unsupported dtype string: {dtype!r}") from None
    if dtype in (float,):
        return VarType.FP32
    if dtype in (int,):
        return VarType.INT64
    if dtype in (bool,):
        return VarType.BOOL
    npdt = np.dtype(dtype)
    try:
        return _NP_TO_VT[npdt]
    except KeyError:
        raise ValueError(f"unsupported dtype: {dtype!r}") from None


def to_numpy_dtype(dtype) -> np.dtype:
    return _VT_TO_NP[convert_dtype(dtype)]


def dtype_name(dtype) -> str:
    return _VT_TO_STR[convert_dtype(dtype)]


def is_float(dtype) -> bool:
    return convert_dtype(dtype) in FLOAT_TYPES
