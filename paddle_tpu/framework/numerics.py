"""Numerics observability: in-program tensor-stats probes, a NaN/Inf
flight recorder, and the health stream they feed.

Every perf rung ahead of this layer (KV page quantization, quantized
collectives, remat/offload) is a *numerics-risk* change, and until now
the only runtime numerics tool was the fail-fast ``FLAGS_check_nan_inf``
(executor.py), which names an op and dies.  This module is the
continuous counterpart — the r13/r15/r17 observability arc applied to
numbers instead of requests or memory:

* **probe stream** — ``numerics_probe_pass`` (framework/ir.py) appends
  cheap in-program stat reductions over selected op outputs
  (grad/param/update-role vars always; ``FLAGS_numerics_probe_ops``
  widens by op-type regex), packed into ONE extra fetched vector per
  step.  Five partials per var — absmax / sum / sum-of-squares /
  finite-count / numel — each with an associative cross-shard combine
  (max or sum), so on the shard_map DP path a shard-resident or
  batch-sharded value reduces its local shard and psums (the
  ``cross_shard_norms`` trick), making the finalized stats
  layout/ZeRO-stage/DP-path-invariant.  Which vars need the combine is
  decided by the shared distribution-state engine
  (``framework/shard_analysis.py variant_names`` — since r26 the same
  abstract interpretation the shard-safety checks run, which also
  audits the packed ``STATS_VAR``'s replication contract after the
  pass).  ``on_step`` finalizes partials into {absmax, mean, rms,
  nonfinite, numel} per var.
* **telemetry** — ``numerics_grad_norm`` / ``numerics_param_norm`` /
  ``numerics_update_ratio`` gauges, ``numerics_nonfinite_total``
  counter, plus the AMP instruments (``amp_found_inf_total``,
  ``amp_loss_scale``) when the program carries dynamic-loss-scaling
  ops.
* **HealthMonitor** — a windowed loss-spike detector + nonfinite
  tripwire with declared thresholds and a ``health()`` read hook shaped
  like ``telemetry.slo_tracker()``'s.
* **NaN/Inf flight recorder** — symmetric to the r15 OOM recorder: when
  the armed ``FLAGS_check_nan_inf`` check (eager or checkify path)
  raises, or the monitor trips, ``record_nan_debris`` dumps the failing
  op, the last-K steps of the per-var stats ring buffer, loss history,
  a telemetry snapshot and the chrome trace into
  ``FLAGS_numerics_debris_dir``; the original exception (if any) keeps
  propagating unchanged.

``FLAGS_numerics_probe=0`` (default) is bit-identical to the unprobed
pipeline: the pass never runs, no extra fetch exists, no instrument is
touched (pinned by tests/test_numerics.py).
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
import traceback
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .core import Block, Program

__all__ = [
    "STATS_VAR", "PARTIALS", "probe_armed", "probe_ops_regex",
    "probe_signature", "select_probe_targets", "finalize", "on_step",
    "capture", "stream", "HealthMonitor", "health_monitor", "health",
    "record_nan_debris", "is_nan_check_error", "maybe_record_check_failure",
    "reset",
]

#: the single packed stats vector the probe pass produces and the
#: executor / DP runner fetch (one extra fetch per step)
STATS_VAR = "@numerics_stats@"

#: per-var partial order inside the packed vector (5 scalars per
#: target).  The nonfinite count is reduced DIRECTLY (sum of the
#: not-isfinite mask): a healthy tensor's partial is a sum of zeros —
#: exact in f32 at any size — where a finite-count/numel subtraction
#: would report phantom nonfinites past 2^24 elements.
PARTIALS = ("absmax", "sum", "sumsq", "nonfinite", "numel")

#: finalized per-var stats on_step derives from the partials
STATS = ("absmax", "mean", "rms", "nonfinite", "numel")

#: float var dtypes eligible for probing (VarType ints resolved lazily)
def _float_dtypes():
    from .dtype import VarType

    return (VarType.FP16, VarType.BF16, VarType.FP32, VarType.FP64)


def probe_armed() -> bool:
    """FLAGS_numerics_probe resolved at call time."""
    from ..utils.flags import flag

    return bool(flag("numerics_probe", False))


def probe_ops_regex() -> str:
    from ..utils.flags import flag

    return str(flag("numerics_probe_ops", "") or "")


def probe_signature():
    """The probe config tuple compile caches key on: flipping the flag
    (or the widening regex) must never serve a compile built under the
    other regime."""
    return (probe_armed(), probe_ops_regex())


# ==========================================================================
# probe target selection (shared by the IR pass and the tools)
# ==========================================================================
def select_probe_targets(program: Program, block: Block,
                         ops_regex: str = "") -> List[dict]:
    """Ordered probe targets for one program: ``[{var, kind, op_index,
    op_type}, ...]`` in program order of each var's LAST writer (the
    probes read final values, so program order is the bisector's
    first-divergence order).

    Kinds: ``grad`` / ``param`` / ``update`` (optimizer-state outputs)
    are always selected; ``loss`` (the var the Backward|Loss seed
    differentiates); ``amp_found`` / ``amp_scale`` (dynamic loss
    scaling); ``op`` for outputs of any op whose type matches
    ``ops_regex``.  Non-float vars, SelectedRows, sub-block-local names
    and probe artifacts are skipped."""
    from ..backward import OP_ROLE_KEY, OP_ROLE_VAR_KEY, OpRole
    from ..parallel import partition_rules
    from .dtype import VarType

    floats = _float_dtypes()
    rx = re.compile(ops_regex) if ops_regex else None
    last_writer: Dict[str, int] = {}
    for i, op_ in enumerate(block.ops):
        for n in op_.output_arg_names:
            if n != "@EMPTY@":
                last_writer[n] = i

    def var_ok(name, allow_bool=False):
        if not name or name == "@EMPTY@" or name == STATS_VAR \
                or name.startswith("@nprobe@"):
            return False
        v = block._find_var_recursive(name)
        if v is None:
            return False
        if getattr(v, "type", None) == VarType.SELECTED_ROWS:
            return False
        if v.dtype in floats:
            return True
        return allow_bool and v.dtype in (VarType.BOOL, VarType.INT32,
                                          VarType.INT64)

    picked: Dict[str, str] = {}  # var -> kind (first pick wins by pass)

    def pick(name, kind, allow_bool=False):
        if name in picked or not var_ok(name, allow_bool):
            return
        picked[name] = kind

    mask = int(OpRole.Backward)
    for i, op_ in enumerate(block.ops):
        role = int(op_.attrs.get(OP_ROLE_KEY, 0) or 0)
        # AMP dynamic loss scaling: the found_inf flag and the live scale
        if op_.type == "amp_check_finite_and_scale":
            for n in op_.outputs.get("FoundInfinite", []):
                pick(n, "amp_found", allow_bool=True)
        if op_.type == "update_loss_scaling":
            for n in op_.outputs.get("LossScalingOut", []):
                pick(n, "amp_scale")
        # loss var: the append_backward seed op (Backward|Loss role)
        # writes `<loss>@GRAD`
        if role == int(OpRole.Backward) | int(OpRole.Loss):
            for n in op_.output_arg_names:
                if n.endswith("@GRAD"):
                    pick(n[: -len("@GRAD")], "loss")
        # grads: op_role_var pairs [param, grad, ...] on backward ops
        if role & mask:
            rv = op_.attrs.get(OP_ROLE_VAR_KEY) or []
            for j in range(1, len(rv), 2):
                pick(rv[j], "grad")
        # update ops — Param+Grad slots cover the per-parameter forms
        # (partition_rules.is_update_op) AND the multi-slot fused ones
        # (fused_sgd/fused_momentum/fused_adam) the optimizer-fusion
        # pass emits before this pass runs: params, grads, and every
        # non-param output (optimizer state) are probed
        if (op_.inputs.get("Param") and op_.inputs.get("Grad")) \
                or partition_rules.is_update_op(op_.type):
            params = op_.inputs.get("Param", [])
            for n in op_.inputs.get("Grad", []):
                pick(n, "grad")
            for n in params:
                pick(n, "param")
            for slot, names in op_.outputs.items():
                for n in names:
                    if n not in params:
                        pick(n, "update")
        if rx is not None and rx.search(op_.type) \
                and op_.attrs.get("op_namescope") != "/numerics_probe/":
            for n in op_.output_arg_names:
                pick(n, "op")

    targets = []
    for name, kind in picked.items():
        i = last_writer.get(name)
        if i is None:
            continue  # scope-only value: no in-program producer to blame
        targets.append({"var": name, "kind": kind, "op_index": i,
                        "op_type": block.ops[i].type})
    targets.sort(key=lambda t: (t["op_index"], t["var"]))
    return targets


# ==========================================================================
# stats stream: ring buffer + telemetry + capture sinks
# ==========================================================================
class NumericsStream:
    """Process-wide probe stream state: a last-K-steps ring buffer of
    per-var finalized stats, the loss history, and any live capture
    sinks (the bisector records through one)."""

    def __init__(self):
        self._lock = threading.Lock()
        from ..utils.flags import flag

        k = max(int(flag("numerics_ring_steps", 8) or 8), 1)
        self.ring: deque = deque(maxlen=k)
        self.loss_history: deque = deque(maxlen=max(8 * k, 64))
        self.step = 0
        self.sinks: List[list] = []

    def record(self, entry: dict):
        with self._lock:
            self.step += 1
            entry = dict(entry, step=self.step)
            self.ring.append(entry)
            if entry.get("loss") is not None:
                self.loss_history.append(
                    {"step": self.step, "loss": entry["loss"]})
            for s in self.sinks:
                s.append(entry)
        return entry

    def ring_list(self) -> List[dict]:
        with self._lock:
            return list(self.ring)

    def losses(self) -> List[dict]:
        with self._lock:
            return list(self.loss_history)


_STREAM: Optional[NumericsStream] = None
_STREAM_LOCK = threading.Lock()


def stream() -> NumericsStream:
    global _STREAM
    if _STREAM is None:
        with _STREAM_LOCK:
            if _STREAM is None:
                _STREAM = NumericsStream()
    return _STREAM


@contextmanager
def capture():
    """Collect every probed step recorded while the context is live —
    the bisector's tap into the stream.  Yields the list the entries
    append to (each: {step, where, loss, stats: {var: {...}},
    order: [var, ...]})."""
    sink: list = []
    s = stream()
    with s._lock:
        s.sinks.append(sink)
    try:
        yield sink
    finally:
        with s._lock:
            if sink in s.sinks:
                s.sinks.remove(sink)


def finalize(layout: Sequence[dict], vec) -> Dict[str, dict]:
    """Partials -> finalized stats, ordered like ``layout``.  ``vec`` is
    the fetched ``STATS_VAR`` vector (5 scalars per target)."""
    vec = np.asarray(vec, dtype=np.float64).reshape(-1)
    out: Dict[str, dict] = {}
    for i, t in enumerate(layout):
        absmax, s, sq, nf, numel = vec[5 * i: 5 * i + 5]
        n = max(float(numel), 0.0)
        mean = float(s / n) if n else 0.0
        rms = float(math.sqrt(max(sq, 0.0) / n)) if n else 0.0
        nonfinite = int(round(max(float(nf), 0.0)))
        out[t["var"]] = {
            "kind": t["kind"], "op_type": t["op_type"],
            "op_index": t["op_index"], "absmax": float(absmax),
            "mean": mean, "rms": rms, "nonfinite": nonfinite,
            "numel": int(round(n)),
        }
    return out


def on_step(layout: Sequence[dict], vec, where: str = "executor"):
    """THE per-step consumer: finalize the fetched partials, feed the
    three consumers (telemetry gauges/counters, the HealthMonitor, any
    capture sinks).  Called by the executor step path and both DP paths
    whenever the probe pass armed a compile."""
    from ..utils import telemetry as tm

    stats = finalize(layout, vec)
    grad_sq = param_sq = 0.0
    nonfinite_total = 0
    loss = None
    amp_found = None
    amp_scale = None
    for var, st in stats.items():
        nonfinite_total += st["nonfinite"]
        k = st["kind"]
        if k == "grad":
            grad_sq += st["rms"] ** 2 * st["numel"]
        elif k == "param":
            param_sq += st["rms"] ** 2 * st["numel"]
        elif k == "loss" and loss is None:
            loss = st["mean"]
        elif k == "amp_found":
            amp_found = st["absmax"] > 0.0
        elif k == "amp_scale":
            amp_scale = st["mean"]
    grad_norm = math.sqrt(grad_sq)
    param_norm = math.sqrt(param_sq)
    tm.gauge("numerics_grad_norm",
             "global gradient norm over probed grad-role vars "
             "(sqrt of cross-var sum of squares)").set(grad_norm)
    tm.gauge("numerics_param_norm",
             "global parameter norm over probed param-role vars").set(
                 param_norm)
    if param_norm > 0.0:
        tm.gauge("numerics_update_ratio",
                 "grad-to-param norm ratio (the weight-relative update "
                 "scale a healthy run keeps roughly constant)").set(
                     grad_norm / param_norm)
    if nonfinite_total:
        tm.counter("numerics_nonfinite_total",
                   "non-finite elements observed across all probed "
                   "vars").inc(nonfinite_total)
    if amp_found is not None:
        if amp_found:
            tm.counter("amp_found_inf_total",
                       "AMP dynamic-loss-scaling steps whose gradients "
                       "contained Inf/NaN (update skipped, scale "
                       "backing off)").inc()
            from ..utils import tracing

            tracing.annotate("amp:found_inf",
                             {"loss_scale": amp_scale, "where": where})
    if amp_scale is not None:
        tm.gauge("amp_loss_scale",
                 "live AMP dynamic loss scale").set(amp_scale)
    entry = stream().record({
        "where": where, "loss": loss,
        "grad_norm": grad_norm, "param_norm": param_norm,
        "nonfinite": nonfinite_total,
        "amp_found_inf": amp_found, "amp_loss_scale": amp_scale,
        "stats": stats, "order": [t["var"] for t in layout],
    })
    health_monitor().observe_step(entry)
    return entry


# ==========================================================================
# HealthMonitor: declared thresholds, health() read hook
# ==========================================================================
UNSET = object()


class HealthMonitor:
    """Windowed numerics health over the probe stream.

    * **nonfinite tripwire** — any probed var with nonfinite elements
      trips (the first such var in program order names the op);
    * **loss-spike detector** — a finite loss more than ``spike_factor``
      times the rolling window mean (after ``min_steps`` warmup) trips;
    * **AMP found_inf** feeds the window context (never trips by
      itself — backing the scale off is the designed response).

    A trip dumps flight-recorder debris (once per trip kind until
    ``reset``) and latches ``health()["healthy"] = False``.  The
    ``health()`` hook is shaped like ``telemetry.slo_tracker()``'s
    ``admission_hint()``: one dict, read per step by whoever closes a
    loop on it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.configure()

    def configure(self, spike_window=UNSET, spike_factor=UNSET,
                  min_steps=UNSET) -> "HealthMonitor":
        from ..utils.flags import flag

        with self._lock:
            self._spike_window = int(
                flag("numerics_spike_window", 32) or 32) \
                if spike_window is UNSET else int(spike_window)
            self._spike_factor = float(
                flag("numerics_spike_factor", 4.0) or 4.0) \
                if spike_factor is UNSET else float(spike_factor)
            self._min_steps = 8 if min_steps is UNSET else int(min_steps)
            self._window: deque = deque(maxlen=max(self._spike_window, 1))
            self._trips: List[dict] = []
            self._dumped_kinds: set = set()
            self._nonfinite_total = 0
            self._last = {}
        return self

    def reset(self):
        self.configure()

    # ------------------------------------------------------------------
    def observe_step(self, entry: dict):
        from ..utils import telemetry as tm

        trips: List[dict] = []
        with self._lock:
            self._last = entry
            self._nonfinite_total += int(entry.get("nonfinite") or 0)
            if entry.get("nonfinite"):
                first = next(
                    (dict(var=v, **{k: st[k] for k in
                                    ("op_type", "op_index", "nonfinite")})
                     for v, st in entry["stats"].items()
                     if st["nonfinite"]), None)
                trips.append({"kind": "nonfinite", "step": entry["step"],
                              "detail": first})
            loss = entry.get("loss")
            if loss is not None and math.isfinite(loss):
                if (len(self._window) >= self._min_steps
                        and loss > self._spike_factor
                        * (sum(self._window) / len(self._window))):
                    trips.append({"kind": "loss_spike",
                                  "step": entry["step"],
                                  "detail": {"loss": loss,
                                             "window_mean":
                                                 sum(self._window)
                                                 / len(self._window),
                                             "factor": self._spike_factor}})
                self._window.append(loss)
            self._trips.extend(trips)
            need_dump = [t for t in trips
                         if t["kind"] not in self._dumped_kinds]
            self._dumped_kinds.update(t["kind"] for t in need_dump)
        for t in trips:
            tm.counter("numerics_health_trips_total",
                       "HealthMonitor trips by kind",
                       labels=("kind",)).labels(kind=t["kind"]).inc()
        for t in need_dump:
            record_nan_debris(f"monitor_{t['kind']}", trip=t)
        return trips

    def observe_loss(self, loss: float, step: Optional[int] = None):
        """Direct feed for training loops that fetch their own loss
        (probe-off runs can still drive the spike detector)."""
        return self.observe_step({"step": step or (stream().step + 1),
                                  "loss": float(loss), "nonfinite": 0,
                                  "stats": {}})

    # ------------------------------------------------------------------
    def health(self) -> Dict:
        """THE read hook: live health + declared thresholds — the
        numerics analog of ``slo_tracker().admission_hint()``."""
        with self._lock:
            last = self._last
            return {
                "healthy": not self._trips,
                "trips": list(self._trips),
                "nonfinite_total": self._nonfinite_total,
                "last_step": last.get("step"),
                "loss": last.get("loss"),
                "grad_norm": last.get("grad_norm"),
                "update_ratio": (
                    (last.get("grad_norm") or 0.0)
                    / last["param_norm"]
                    if last.get("param_norm") else None),
                "amp_loss_scale": last.get("amp_loss_scale"),
                "thresholds": {"spike_window": self._spike_window,
                               "spike_factor": self._spike_factor,
                               "min_steps": self._min_steps},
            }


_MONITOR: Optional[HealthMonitor] = None
_MONITOR_LOCK = threading.Lock()


def health_monitor() -> HealthMonitor:
    global _MONITOR
    if _MONITOR is None:
        with _MONITOR_LOCK:
            if _MONITOR is None:
                _MONITOR = HealthMonitor()
    return _MONITOR


def health() -> Dict:
    return health_monitor().health()


def reset():
    """Fresh stream + monitor (tests / new measurement windows)."""
    global _STREAM, _MONITOR
    with _STREAM_LOCK:
        _STREAM = None
    with _MONITOR_LOCK:
        _MONITOR = None


# ==========================================================================
# NaN/Inf flight recorder (symmetric to memory_plan.record_oom_debris)
# ==========================================================================
_debris_lock = threading.Lock()
_debris_seq = 0

#: substring both NaN-check paths emit (executor._eager_nan_check and
#: the checkify message share the format string)
_CHECK_MARKER = "contains Inf/Nan"
_CHECK_OP_RE = re.compile(r"Operator '([^']+)' output '([^']+)'")


def is_nan_check_error(exc: BaseException) -> bool:
    """True when ``exc`` is the FLAGS_check_nan_inf sentinel (raised by
    the eager per-op check or re-raised from the checkify path)."""
    return _CHECK_MARKER in f"{exc}"


def maybe_record_check_failure(where: str, exc: BaseException,
                               program: Optional[Program] = None):
    """Step-path hook: dump NaN debris when the armed check tripped,
    then let the caller re-raise unchanged.  Never raises."""
    try:
        if is_nan_check_error(exc):
            record_nan_debris(where, exc=exc, program=program)
    except Exception:
        pass


def record_nan_debris(where: str, exc: Optional[BaseException] = None,
                      trip: Optional[dict] = None,
                      program: Optional[Program] = None) -> Optional[str]:
    """Dump a post-mortem debris directory for a numerics failure: the
    failing op (parsed from the check's error, or the monitor trip
    detail), the last-K steps of the per-var stats ring buffer, the
    loss history, a telemetry snapshot and the profiler's chrome trace.
    Returns the directory path, or None when
    ``FLAGS_numerics_debris_dir`` is unset.  Never raises — a caught
    exception must keep propagating unchanged."""
    from ..utils.flags import flag

    root = flag("numerics_debris_dir") or ""
    if not root:
        return None
    global _debris_seq
    try:
        with _debris_lock:
            _debris_seq += 1
            seq = _debris_seq
        d = os.path.join(str(root), f"nan_{where}_{os.getpid()}_{seq}")
        os.makedirs(d, exist_ok=True)
        failing = None
        if exc is not None:
            m = _CHECK_OP_RE.search(f"{exc}")
            if m:
                failing = {"op_type": m.group(1), "var": m.group(2)}
            with open(os.path.join(d, "error.txt"), "w") as f:
                f.write(f"where: {where}\n")
                f.write(f"type: {type(exc).__name__}\n")
                f.write(f"error: {exc}\n\n")
                f.write("".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__)))
        if trip is not None and failing is None:
            det = trip.get("detail") or {}
            if det.get("var"):
                failing = {"op_type": det.get("op_type"),
                           "var": det.get("var")}
        s = stream()
        with open(os.path.join(d, "debris.json"), "w") as f:
            json.dump({
                "where": where, "failing_op": failing, "trip": trip,
                "health": health_monitor().health(),
                "stats_ring": s.ring_list(),
                "loss_history": s.losses(),
            }, f, indent=2, default=str)
        try:
            from ..utils import telemetry

            with open(os.path.join(d, "telemetry.json"), "w") as f:
                json.dump(telemetry.snapshot(), f, indent=2)
        except Exception:
            pass
        try:
            from .. import profiler

            events = profiler.get_events()
            if events:
                profiler._write_chrome_trace(
                    events, os.path.join(d, "trace.json"))
        except Exception:
            pass
        if program is not None:
            try:
                counts: Dict[str, int] = {}
                for blk in program.blocks:
                    for op_ in blk.ops:
                        counts[op_.type] = counts.get(op_.type, 0) + 1
                with open(os.path.join(d, "program.json"), "w") as f:
                    json.dump({"op_counts": counts}, f, indent=2)
            except Exception:
                pass
        return d
    except Exception:
        return None
