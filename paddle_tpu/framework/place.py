"""Device placement model.

Capability parity with the reference's ``platform::Place`` tagged union
(reference: paddle/fluid/platform/place.h:1) — but TPU-first: the native
accelerator place is :class:`TPUPlace`, and every place resolves to a JAX
device.  ``CUDAPlace`` is kept as a compatibility alias that resolves to the
accelerator if present (so reference-style scripts run with only a Place
swap, per the north star).
"""
from __future__ import annotations

import functools


class Place:
    device_id: int = 0

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        raise NotImplementedError


class CPUPlace(Place):
    def __init__(self):
        self.device_id = 0

    def __repr__(self):
        return "CPUPlace"

    def jax_device(self):
        import jax

        return jax.devices("cpu")[0]


class TPUPlace(Place):
    """The accelerator place — `fluid.TPUPlace()` per the north star."""

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def jax_device(self):
        import jax

        devs = _accelerator_devices()
        if not devs:
            raise RuntimeError(
                "TPUPlace requested but no accelerator device is available"
            )
        return devs[self.device_id % len(devs)]


class CUDAPlace(TPUPlace):
    """Compatibility alias: reference scripts using CUDAPlace(0) run on the
    accelerator (or CPU if none) without modification."""


class TPUPinnedPlace(CPUPlace):
    """Host-staging place (reference: CUDAPinnedPlace). On TPU, host staging
    is managed by jax.device_put; this is an API-compat shim."""


CUDAPinnedPlace = TPUPinnedPlace


@functools.lru_cache(maxsize=1)
def _accelerator_devices():
    import jax

    devs = jax.devices()
    if devs and devs[0].platform != "cpu":
        return tuple(devs)
    return ()


def is_compiled_with_tpu() -> bool:
    return bool(_accelerator_devices())


# Reference API-compat name.
def is_compiled_with_cuda() -> bool:
    return bool(_accelerator_devices())


def _get_paddle_place(place):
    """Normalize str/None/Place to a Place (reference: framework.py helpers)."""
    if place is None:
        return TPUPlace(0) if is_compiled_with_tpu() else CPUPlace()
    if isinstance(place, Place):
        return place
    if isinstance(place, str):
        p = place.lower()
        if p == "cpu":
            return CPUPlace()
        if p.startswith(("tpu", "gpu", "cuda", "xpu")):
            idx = p.split(":")[1] if ":" in p else 0
            return TPUPlace(int(idx))
    raise ValueError(f"unknown place: {place!r}")
