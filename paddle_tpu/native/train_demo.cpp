// No-Python training demo (reference: paddle/fluid/train/demo/
// demo_trainer.cc:1 — load a saved program desc and train it from C++
// without Python).
//
// TPU-native equivalent: the TRAIN STEP is exported as a StableHLO
// module (inference/export.py export_train_step) whose main is
//   main(state..., feeds...) -> (fetches..., new_state...)
// — every parameter / optimizer moment is explicit module IO.  This
// binary loads the module through the same PJRT C-API runtime the
// native predictor uses (predictor_capi.cpp), seeds the state from
// state.ptw, and drives the training loop in pure C++: feed a batch,
// run one step, carry the state outputs back into the state inputs.
// No Python anywhere in the loop.
//
// Usage: train_demo <export_dir> <pjrt_plugin.so> [steps] [options_file]
//   options_file (optional): newline-separated PJRT create-options
//   ("name int N" / "name str S"), for plugins that need them.
//   Feeds come from <export_dir>/data.ptw when present, else the demo
//   synthesizes deterministic batches.
//
// Build (see tests/test_train_demo.py):
//   g++ -O3 -std=c++17 train_demo.cpp predictor_capi.cpp -ldl \
//       -I<tensorflow include> -o train_demo

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "pd_inference_c_api.h"

namespace {

struct PtwTensor {
  int dtype = 0;
  std::vector<int64_t> dims;
  std::vector<char> data;
};

bool read_ptw_file(const std::string& path,
                   std::map<std::string, PtwTensor>* out,
                   std::vector<std::string>* order) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  char magic[4];
  f.read(magic, 4);
  if (std::memcmp(magic, "PTW1", 4) != 0) return false;
  uint32_t n = 0;
  f.read((char*)&n, 4);
  for (uint32_t i = 0; i < n; ++i) {
    uint16_t nl = 0;
    f.read((char*)&nl, 2);
    std::string name(nl, '\0');
    f.read(&name[0], nl);
    uint8_t code = 0, ndim = 0;
    f.read((char*)&code, 1);
    f.read((char*)&ndim, 1);
    PtwTensor t;
    t.dtype = code;
    for (int d = 0; d < ndim; ++d) {
      uint32_t dim = 0;
      f.read((char*)&dim, 4);
      t.dims.push_back((int64_t)dim);
    }
    uint64_t nb = 0;
    f.read((char*)&nb, 8);
    t.data.resize(nb);
    f.read(t.data.data(), (std::streamsize)nb);
    if (!f) return false;
    (*out)[name] = std::move(t);
    if (order) order->push_back(name);
  }
  return true;
}

size_t dtype_size(int code) {
  switch (code) {
    case PD_FLOAT64: case PD_INT64: return 8;
    case PD_BFLOAT16: case PD_FLOAT16: return 2;
    case PD_UINT8: case PD_INT8: case PD_BOOL: return 1;
    default: return 4;
  }
}

// deterministic synthetic batch: uniforms for float feeds, small ints
// for integer feeds (labels)
void fill_synthetic(PD_NativeTensor* t, uint64_t* rng_state) {
  int64_t n = 1;
  for (int i = 0; i < t->ndim; ++i) n *= t->dims[i];
  auto next = [&]() {
    uint64_t x = *rng_state += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
  };
  if (t->dtype == PD_FLOAT32) {
    float* p = (float*)t->data;
    for (int64_t i = 0; i < n; ++i)
      p[i] = (float)((next() >> 11) * (1.0 / 9007199254740992.0));
  } else if (t->dtype == PD_INT64) {
    int64_t* p = (int64_t*)t->data;
    for (int64_t i = 0; i < n; ++i) p[i] = (int64_t)(next() % 10);
  } else if (t->dtype == PD_INT32) {
    int32_t* p = (int32_t*)t->data;
    for (int64_t i = 0; i < n; ++i) p[i] = (int32_t)(next() % 10);
  } else {
    std::memset(t->data, 0, n * dtype_size(t->dtype));
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: %s <export_dir> <pjrt_plugin.so> [steps]\n",
                 argv[0]);
    return 2;
  }
  std::string dir = argv[1];
  const char* plugin = argv[2];
  int steps = argc > 3 ? std::atoi(argv[3]) : 10;
  std::string options;
  if (argc > 4) {
    std::ifstream of(argv[4]);
    std::stringstream ss;
    ss << of.rdbuf();
    options = ss.str();
  }

  PD_NativePredictor* pred =
      PD_NativePredictorCreate(dir.c_str(), plugin, options.c_str());
  if (!pred) {
    std::fprintf(stderr, "create failed: %s\n", PD_NativeLastError());
    return 1;
  }
  int n_in = PD_NativePredictorNumInputs(pred);
  int n_out = PD_NativePredictorNumOutputs(pred);

  // initial state
  std::map<std::string, PtwTensor> state;
  if (!read_ptw_file(dir + "/state.ptw", &state, nullptr)) {
    std::fprintf(stderr, "missing %s/state.ptw (export with "
                         "export_train_step)\n", dir.c_str());
    return 1;
  }
  // optional real data
  std::map<std::string, PtwTensor> data;
  read_ptw_file(dir + "/data.ptw", &data, nullptr);

  // input metadata from the predictor
  std::vector<PD_NativeTensor> ins(n_in);
  std::vector<std::vector<char>> in_bufs(n_in);
  std::vector<std::string> in_names(n_in);
  uint64_t rng = 0x1234567ull;
  for (int i = 0; i < n_in; ++i) {
    PD_NativeTensor t;
    if (PD_NativePredictorInputInfo(pred, i, &t) != 0) {
      std::fprintf(stderr, "input info %d failed\n", i);
      return 1;
    }
    in_names[i] = PD_NativePredictorInputName(pred, i);
    int64_t n = 1;
    for (int d = 0; d < t.ndim; ++d) n *= t.dims[d];
    in_bufs[i].resize((size_t)n * dtype_size(t.dtype));
    t.data = in_bufs[i].data();
    auto it = state.find(in_names[i]);
    if (it != state.end()) {
      std::memcpy(t.data, it->second.data.data(),
                  std::min(in_bufs[i].size(), it->second.data.size()));
    }
    ins[i] = t;
  }

  std::vector<PD_NativeTensor> outs(n_out);
  std::vector<std::string> out_names(n_out);
  for (int i = 0; i < n_out; ++i)
    out_names[i] = PD_NativePredictorOutputName(pred, i);

  std::printf("train_demo: %d inputs, %d outputs, %d steps\n",
              n_in, n_out, steps);
  for (int step = 0; step < steps; ++step) {
    // fill feed inputs (non-state): real data if provided, else synthetic
    for (int i = 0; i < n_in; ++i) {
      if (state.count(in_names[i])) continue;  // state slot: carried
      auto it = data.find(in_names[i]);
      if (it != data.end()) {
        std::memcpy(ins[i].data, it->second.data.data(),
                    std::min(in_bufs[i].size(), it->second.data.size()));
      } else {
        fill_synthetic(&ins[i], &rng);
      }
    }
    int got = PD_NativePredictorRun(pred, ins.data(), n_in, outs.data(),
                                    n_out);
    if (got < 0) {
      std::fprintf(stderr, "run failed at step %d: %s\n", step,
                   PD_NativeLastError());
      return 1;
    }
    // loss = first output (scalar-ish): print its first element
    if (got > 0 && outs[0].dtype == PD_FLOAT32 && outs[0].data) {
      std::printf("step %d loss %.6f\n", step, ((float*)outs[0].data)[0]);
    }
    // carry state: copy matching outputs back into state inputs
    for (int o = 0; o < got; ++o) {
      for (int i = 0; i < n_in; ++i) {
        if (out_names[o] == in_names[i] && outs[o].data) {
          int64_t n = 1;
          for (int d = 0; d < outs[o].ndim; ++d) n *= outs[o].dims[d];
          std::memcpy(ins[i].data, outs[o].data,
                      std::min(in_bufs[i].size(),
                               (size_t)n * dtype_size(outs[o].dtype)));
        }
      }
    }
    for (int o = 0; o < got; ++o) PD_NativeTensorFree(&outs[o]);
  }
  std::printf("train_demo: done\n");
  PD_NativePredictorDestroy(pred);
  return 0;
}
