// AES-128/192/256 CTR-mode cipher for encrypted model save/load.
//
// Reference analog: paddle/fluid/framework/io/crypto/ (AESCipher over
// cryptopp, cipher_utils.cc key generation) + pybind/crypto.cc.  This
// build has no third-party crypto dependency, so the AES block cipher
// is implemented here directly (FIPS-197 forward cipher; CTR mode needs
// no inverse cipher), exposed through a small C API consumed by
// paddle_tpu/utils/crypto.py via ctypes.
//
// CTR layout: the 16-byte IV is the initial counter block; big-endian
// increment of the low 8 bytes per block.  Same operation encrypts and
// decrypts.

#include <stdint.h>
#include <string.h>

namespace {

const uint8_t kSbox[256] = {
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67,
    0x2b, 0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59,
    0x47, 0xf0, 0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7,
    0xfd, 0x93, 0x26, 0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1,
    0x71, 0xd8, 0x31, 0x15, 0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05,
    0x9a, 0x07, 0x12, 0x80, 0xe2, 0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83,
    0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0, 0x52, 0x3b, 0xd6, 0xb3, 0x29,
    0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed, 0x20, 0xfc, 0xb1, 0x5b,
    0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf, 0xd0, 0xef, 0xaa,
    0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f, 0x50, 0x3c,
    0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5, 0xbc,
    0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19,
    0x73, 0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee,
    0xb8, 0x14, 0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49,
    0x06, 0x24, 0x5c, 0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79,
    0xe7, 0xc8, 0x37, 0x6d, 0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4,
    0xea, 0x65, 0x7a, 0xae, 0x08, 0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6,
    0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f, 0x4b, 0xbd, 0x8b, 0x8a, 0x70,
    0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e, 0x61, 0x35, 0x57, 0xb9,
    0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11, 0x69, 0xd9, 0x8e,
    0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf, 0x8c, 0xa1,
    0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f, 0xb0,
    0x54, 0xbb, 0x16};

const uint8_t kRcon[11] = {0x00, 0x01, 0x02, 0x04, 0x08, 0x10,
                           0x20, 0x40, 0x80, 0x1b, 0x36};

inline uint8_t xtime(uint8_t x) {
  return static_cast<uint8_t>((x << 1) ^ ((x >> 7) * 0x1b));
}

struct AesKey {
  uint8_t round_keys[15 * 16];
  int nr;  // rounds: 10/12/14
};

// FIPS-197 key expansion for 128/192/256-bit keys.
bool key_expand(const uint8_t* key, int key_len, AesKey* out) {
  int nk;
  if (key_len == 16) {
    nk = 4;
    out->nr = 10;
  } else if (key_len == 24) {
    nk = 6;
    out->nr = 12;
  } else if (key_len == 32) {
    nk = 8;
    out->nr = 14;
  } else {
    return false;
  }
  uint8_t* w = out->round_keys;
  memcpy(w, key, static_cast<size_t>(key_len));
  int total_words = 4 * (out->nr + 1);
  for (int i = nk; i < total_words; ++i) {
    uint8_t t[4];
    memcpy(t, w + 4 * (i - 1), 4);
    if (i % nk == 0) {
      uint8_t tmp = t[0];  // RotWord
      t[0] = kSbox[t[1]];
      t[1] = kSbox[t[2]];
      t[2] = kSbox[t[3]];
      t[3] = kSbox[tmp];
      t[0] ^= kRcon[i / nk];
    } else if (nk > 6 && i % nk == 4) {
      for (int j = 0; j < 4; ++j) t[j] = kSbox[t[j]];
    }
    for (int j = 0; j < 4; ++j) {
      w[4 * i + j] = static_cast<uint8_t>(w[4 * (i - nk) + j] ^ t[j]);
    }
  }
  return true;
}

void encrypt_block(const AesKey& k, const uint8_t in[16], uint8_t out[16]) {
  uint8_t s[16];
  for (int i = 0; i < 16; ++i) s[i] = in[i] ^ k.round_keys[i];
  for (int round = 1; round <= k.nr; ++round) {
    // SubBytes
    for (int i = 0; i < 16; ++i) s[i] = kSbox[s[i]];
    // ShiftRows (state is column-major: s[4c + r])
    uint8_t t;
    t = s[1]; s[1] = s[5]; s[5] = s[9]; s[9] = s[13]; s[13] = t;
    t = s[2]; s[2] = s[10]; s[10] = t; t = s[6]; s[6] = s[14]; s[14] = t;
    t = s[15]; s[15] = s[11]; s[11] = s[7]; s[7] = s[3]; s[3] = t;
    if (round != k.nr) {
      // MixColumns
      for (int c = 0; c < 4; ++c) {
        uint8_t* col = s + 4 * c;
        uint8_t a0 = col[0], a1 = col[1], a2 = col[2], a3 = col[3];
        uint8_t all_x = static_cast<uint8_t>(a0 ^ a1 ^ a2 ^ a3);
        col[0] = static_cast<uint8_t>(a0 ^ all_x ^ xtime(a0 ^ a1));
        col[1] = static_cast<uint8_t>(a1 ^ all_x ^ xtime(a1 ^ a2));
        col[2] = static_cast<uint8_t>(a2 ^ all_x ^ xtime(a2 ^ a3));
        col[3] = static_cast<uint8_t>(a3 ^ all_x ^ xtime(a3 ^ a0));
      }
    }
    // AddRoundKey
    const uint8_t* rk = k.round_keys + 16 * round;
    for (int i = 0; i < 16; ++i) s[i] = static_cast<uint8_t>(s[i] ^ rk[i]);
  }
  memcpy(out, s, 16);
}

}  // namespace

extern "C" {

// CTR transform (encrypt == decrypt).  Returns 0 on success.
int PD_AesCtrCrypt(const uint8_t* key, int key_len, const uint8_t iv[16],
                   const uint8_t* in, uint8_t* out, uint64_t n) {
  AesKey k;
  if (!key_expand(key, key_len, &k)) return 1;
  uint8_t counter[16];
  memcpy(counter, iv, 16);
  uint8_t stream[16];
  uint64_t off = 0;
  while (off < n) {
    encrypt_block(k, counter, stream);
    uint64_t chunk = (n - off < 16) ? (n - off) : 16;
    for (uint64_t i = 0; i < chunk; ++i) {
      out[off + i] = static_cast<uint8_t>(in[off + i] ^ stream[i]);
    }
    off += chunk;
    // big-endian increment of the low 8 counter bytes
    for (int i = 15; i >= 8; --i) {
      if (++counter[i] != 0) break;
    }
  }
  return 0;
}

// Single-block forward cipher, exposed so the binding can verify the
// implementation against FIPS-197 test vectors.
int PD_AesEncryptBlock(const uint8_t* key, int key_len,
                       const uint8_t in[16], uint8_t out[16]) {
  AesKey k;
  if (!key_expand(key, key_len, &k)) return 1;
  encrypt_block(k, in, out);
  return 0;
}

}  // extern "C"
