// Native parameter-server table store.
//
// Capability parity with the reference's C++ PS runtime: the dense/sparse
// table storage + server-side optimize step of
// paddle/fluid/operators/distributed/ (request_handler_impl.cc SendVar/
// GetVar handlers running optimize blocks) and the pslib downpour table
// shapes (framework/fleet/fleet_wrapper.h PullSparseVarsSync/
// PushSparseVarsWithLabelAsync).  TPU-native split: XLA owns device math;
// this C++ store owns the host-side trillion-parameter sparse state —
// sharded hash tables with per-shard locks, lazily-initialized embedding
// rows, and fused server-side SGD/Adagrad/Adam appliers.  Transport is
// pluggable (Python TCP service in distributed_ps/service.py; C ABI here).
//
// Build: g++ -O3 -shared -fPIC (see native/build.py).

#include <cstdint>
#include <cstring>
#include <cmath>
#include <atomic>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

enum OptType : int32_t { OPT_SGD = 0, OPT_ADAGRAD = 1, OPT_ADAM = 2,
                         OPT_MOMENTUM = 3 };

struct Optimizer {
  int32_t type = OPT_SGD;
  float lr = 0.01f;
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f, mu = 0.9f;
};

struct DenseTable {
  std::vector<float> data;
  std::vector<float> m1, m2, vel;  // optimizer state
  double beta1_pow = 1.0, beta2_pow = 1.0;
  Optimizer opt;
  std::mutex mu_;

  void init(const float* src, int64_t n) {
    std::lock_guard<std::mutex> g(mu_);
    data.assign(src, src + n);
    m1.assign(n, 0.f);
    m2.assign(n, 0.f);
    vel.assign(n, 0.f);
    beta1_pow = beta2_pow = 1.0;
  }

  void pull(float* dst) {
    std::lock_guard<std::mutex> g(mu_);
    std::memcpy(dst, data.data(), data.size() * sizeof(float));
  }

  void push_grad(const float* grad, int64_t n) {
    std::lock_guard<std::mutex> g(mu_);
    apply(data.data(), grad, n);
  }

  void apply(float* w, const float* g, int64_t n) {
    switch (opt.type) {
      case OPT_SGD:
        for (int64_t i = 0; i < n; ++i) w[i] -= opt.lr * g[i];
        break;
      case OPT_MOMENTUM:
        for (int64_t i = 0; i < n; ++i) {
          vel[i] = opt.mu * vel[i] + g[i];
          w[i] -= opt.lr * vel[i];
        }
        break;
      case OPT_ADAGRAD:
        for (int64_t i = 0; i < n; ++i) {
          m2[i] += g[i] * g[i];
          w[i] -= opt.lr * g[i] / (std::sqrt(m2[i]) + opt.eps);
        }
        break;
      case OPT_ADAM: {
        beta1_pow *= opt.beta1;
        beta2_pow *= opt.beta2;
        float lr_t = opt.lr * std::sqrt(1.0 - beta2_pow) / (1.0 - beta1_pow);
        for (int64_t i = 0; i < n; ++i) {
          m1[i] = opt.beta1 * m1[i] + (1.f - opt.beta1) * g[i];
          m2[i] = opt.beta2 * m2[i] + (1.f - opt.beta2) * g[i] * g[i];
          w[i] -= lr_t * m1[i] / (std::sqrt(m2[i]) + opt.eps);
        }
        break;
      }
    }
  }
};

constexpr int kShards = 32;

struct SparseRow {
  std::vector<float> w;
  std::vector<float> m2;  // adagrad accumulator
  uint32_t unseen_days = 0;
};

struct SparseShard {
  std::unordered_map<int64_t, SparseRow> rows;
  std::mutex mu_;
};

struct SparseTable {
  int64_t dim;
  float init_range = 0.01f;
  Optimizer opt;
  SparseShard shards[kShards];
  uint64_t seed = 0x9e3779b97f4a7c15ull;

  SparseRow& row(int64_t id, SparseShard& sh) {
    auto it = sh.rows.find(id);
    if (it == sh.rows.end()) {
      SparseRow r;
      r.w.resize(dim);
      r.m2.assign(dim, 0.f);
      // deterministic per-id init (splitmix64 -> uniform)
      uint64_t x = (uint64_t)id * 0x9e3779b97f4a7c15ull + seed;
      for (int64_t d = 0; d < dim; ++d) {
        x += 0x9e3779b97f4a7c15ull;
        uint64_t z = x;
        z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
        z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
        z = z ^ (z >> 31);
        float u = (float)(z >> 11) * (1.0f / 9007199254740992.0f);  // [0,1)
        r.w[d] = (2.f * u - 1.f) * init_range;
      }
      it = sh.rows.emplace(id, std::move(r)).first;
    }
    return it->second;
  }

  void pull(const int64_t* ids, int64_t n, float* out) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t id = ids[i];
      SparseShard& sh = shards[((uint64_t)id) % kShards];
      std::lock_guard<std::mutex> g(sh.mu_);
      SparseRow& r = row(id, sh);
      r.unseen_days = 0;
      std::memcpy(out + i * dim, r.w.data(), dim * sizeof(float));
    }
  }

  void push_grad(const int64_t* ids, int64_t n, const float* grads) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t id = ids[i];
      SparseShard& sh = shards[((uint64_t)id) % kShards];
      std::lock_guard<std::mutex> g(sh.mu_);
      SparseRow& r = row(id, sh);
      r.unseen_days = 0;
      const float* gr = grads + i * dim;
      switch (opt.type) {
        case OPT_ADAGRAD:
          for (int64_t d = 0; d < dim; ++d) {
            r.m2[d] += gr[d] * gr[d];
            r.w[d] -= opt.lr * gr[d] / (std::sqrt(r.m2[d]) + opt.eps);
          }
          break;
        default:
          for (int64_t d = 0; d < dim; ++d) r.w[d] -= opt.lr * gr[d];
      }
    }
  }

  int64_t size() {
    int64_t total = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> g(sh.mu_);
      total += (int64_t)sh.rows.size();
    }
    return total;
  }

  // shrink: age all rows one tick, then drop rows whose age reached
  // `days` ticks without a pull/push touching them (accesses reset the
  // age).  days <= 0 is a no-op so a default shrink() can never wipe the
  // table.  (reference: fleet_wrapper.h:232-259 SaveModel/Shrink)
  int64_t shrink(int64_t days) {
    if (days <= 0) return 0;
    int64_t dropped = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> g(sh.mu_);
      for (auto it = sh.rows.begin(); it != sh.rows.end();) {
        if (++it->second.unseen_days >= (uint32_t)days) {
          it = sh.rows.erase(it);
          ++dropped;
        } else {
          ++it;
        }
      }
    }
    return dropped;
  }

  int64_t export_rows(int64_t* ids_out, float* w_out, int64_t cap) {
    int64_t k = 0;
    for (auto& sh : shards) {
      std::lock_guard<std::mutex> g(sh.mu_);
      for (auto& kv : sh.rows) {
        if (k >= cap) return k;
        ids_out[k] = kv.first;
        std::memcpy(w_out + k * dim, kv.second.w.data(), dim * sizeof(float));
        ++k;
      }
    }
    return k;
  }

  void import_rows(const int64_t* ids, const float* ws, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t id = ids[i];
      SparseShard& sh = shards[((uint64_t)id) % kShards];
      std::lock_guard<std::mutex> g(sh.mu_);
      SparseRow r;
      r.w.assign(ws + i * dim, ws + (i + 1) * dim);
      r.m2.assign(dim, 0.f);
      sh.rows[id] = std::move(r);
    }
  }
};

std::vector<DenseTable*> g_dense;
std::vector<SparseTable*> g_sparse;
std::mutex g_mu;

// copy the table pointer under g_mu: a concurrent create's push_back may
// reallocate the vector while another connection thread is reading it
DenseTable* dense_at(int32_t tid) {
  std::lock_guard<std::mutex> g(g_mu);
  if (tid < 0 || tid >= (int32_t)g_dense.size()) return nullptr;
  return g_dense[tid];
}

SparseTable* sparse_at(int32_t tid) {
  std::lock_guard<std::mutex> g(g_mu);
  if (tid < 0 || tid >= (int32_t)g_sparse.size()) return nullptr;
  return g_sparse[tid];
}

}  // namespace

extern "C" {

int32_t ps_create_dense(int64_t size, int32_t opt_type, float lr, float mu,
                        float beta1, float beta2, float eps) {
  auto* t = new DenseTable();
  t->data.assign(size, 0.f);
  t->m1.assign(size, 0.f);
  t->m2.assign(size, 0.f);
  t->vel.assign(size, 0.f);
  t->opt = {opt_type, lr, beta1, beta2, eps, mu};
  std::lock_guard<std::mutex> g(g_mu);
  g_dense.push_back(t);
  return (int32_t)g_dense.size() - 1;
}

void ps_init_dense(int32_t tid, const float* src, int64_t n) {
  if (auto* t = dense_at(tid)) t->init(src, n);
}

void ps_pull_dense(int32_t tid, float* dst) {
  if (auto* t = dense_at(tid)) t->pull(dst);
}

void ps_push_dense_grad(int32_t tid, const float* grad, int64_t n) {
  if (auto* t = dense_at(tid)) t->push_grad(grad, n);
}

int64_t ps_dense_size(int32_t tid) {
  auto* t = dense_at(tid);
  return t ? (int64_t)t->data.size() : -1;
}

int32_t ps_create_sparse(int64_t dim, float init_range, int32_t opt_type,
                         float lr, float eps, uint64_t seed) {
  auto* t = new SparseTable();
  t->dim = dim;
  t->init_range = init_range;
  t->opt.type = opt_type;
  t->opt.lr = lr;
  t->opt.eps = eps;
  t->seed = seed;
  std::lock_guard<std::mutex> g(g_mu);
  g_sparse.push_back(t);
  return (int32_t)g_sparse.size() - 1;
}

void ps_pull_sparse(int32_t tid, const int64_t* ids, int64_t n, float* out) {
  if (auto* t = sparse_at(tid)) t->pull(ids, n, out);
}

void ps_push_sparse_grad(int32_t tid, const int64_t* ids, int64_t n,
                         const float* grads) {
  if (auto* t = sparse_at(tid)) t->push_grad(ids, n, grads);
}

int64_t ps_sparse_size(int32_t tid) {
  auto* t = sparse_at(tid);
  return t ? t->size() : -1;
}

int64_t ps_sparse_shrink(int32_t tid, int64_t days) {
  auto* t = sparse_at(tid);
  return t ? t->shrink(days) : 0;
}

int64_t ps_sparse_export(int32_t tid, int64_t* ids, float* ws, int64_t cap) {
  auto* t = sparse_at(tid);
  return t ? t->export_rows(ids, ws, cap) : 0;
}

void ps_sparse_import(int32_t tid, const int64_t* ids, const float* ws,
                      int64_t n) {
  if (auto* t = sparse_at(tid)) t->import_rows(ids, ws, n);
}

void ps_set_lr(int32_t dense_tid, float lr) {
  if (auto* t = dense_at(dense_tid)) t->opt.lr = lr;
}

// ==========================================================================
// Binary-framed data-plane server (reference:
// operators/distributed/grpc/grpc_server.cc — the native RPC transport;
// here: length-framed binary protocol, one handler thread per trainer
// connection, no Python/GIL on the hot path).
//
// request : [u8 op][u16 name_len][name][u64 c1][payload1][u64 c2][payload2]
//   op 1 PULL_DENSE  (c1=0)                      -> [u8 0][u64 n][floats]
//   op 2 PUSH_DENSE  (c1 floats)                 -> [u8 0][u64 0]
//   op 3 PULL_SPARSE (c1 int64 ids)              -> [u8 0][u64 n*dim][floats]
//   op 4 PUSH_SPARSE (c1 int64 ids, c2 floats)   -> [u8 0][u64 0]
//   op 5 INIT_DENSE  (c1 floats)                 -> [u8 0][u64 0]
//   op 6 PUSH_DELTA  (c1 floats; param += delta) -> [u8 0][u64 0]
// error reply: [u8 1][u64 0]
// ==========================================================================
namespace {

struct NameEntry { int32_t kind; int32_t tid; };  // kind 0=dense 1=sparse
std::unordered_map<std::string, NameEntry> g_names;
std::mutex g_names_mu;

// per-listener state: multiple PSServer instances in one process each
// own their listener; stop() must only touch its own (a process-global
// fd singleton would let instance A's stop kill instance B's server)
struct Listener {
  int fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::mutex fd_mu;     // serializes close (accept thread) vs shutdown (stop)
  bool closed = false;  // guarded by fd_mu
};
std::mutex g_listeners_mu;
std::vector<Listener*> g_listeners;  // parked forever once stopped

// an adversarial/buggy client must not be able to make the server
// allocate unbounded memory or abort: cap per-request element counts
constexpr uint64_t kMaxElems = (1ull << 31);

bool read_exact(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= (size_t)k;
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) return false;
    p += k;
    n -= (size_t)k;
  }
  return true;
}

bool reply(int fd, uint8_t status, const float* data, uint64_t n) {
  if (!write_all(fd, &status, 1)) return false;
  if (!write_all(fd, &n, 8)) return false;
  if (n && !write_all(fd, data, n * sizeof(float))) return false;
  return true;
}

void handle_conn_impl(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::vector<int64_t> ids;
  std::vector<float> floats, out;
  for (;;) {
    uint8_t op;
    uint16_t name_len;
    if (!read_exact(fd, &op, 1) || !read_exact(fd, &name_len, 2)) break;
    std::string name(name_len, '\0');
    if (name_len && !read_exact(fd, &name[0], name_len)) break;
    uint64_t c1 = 0;
    if (!read_exact(fd, &c1, 8)) break;
    if (c1 > kMaxElems) break;  // malformed/hostile frame: drop the conn
    bool want_ids = (op == 3 || op == 4);
    bool ok_read = true;
    if (want_ids) {
      ids.resize(c1);
      ok_read = !c1 || read_exact(fd, ids.data(), c1 * sizeof(int64_t));
    } else {
      floats.resize(c1);
      ok_read = !c1 || read_exact(fd, floats.data(), c1 * sizeof(float));
    }
    if (!ok_read) break;
    uint64_t c2 = 0;
    if (op == 4) {
      if (!read_exact(fd, &c2, 8)) break;
      if (c2 > kMaxElems) break;
      floats.resize(c2);
      if (c2 && !read_exact(fd, floats.data(), c2 * sizeof(float))) break;
    }
    NameEntry ent{-1, -1};
    {
      std::lock_guard<std::mutex> g(g_names_mu);
      auto it = g_names.find(name);
      if (it != g_names.end()) ent = it->second;
    }
    bool ok = false;
    switch (op) {
      case 1: {  // PULL_DENSE
        DenseTable* t = ent.kind == 0 ? dense_at(ent.tid) : nullptr;
        if (t) {
          out.resize(t->data.size());
          t->pull(out.data());
          ok = reply(fd, 0, out.data(), out.size());
        }
        break;
      }
      case 2: {  // PUSH_DENSE
        DenseTable* t = ent.kind == 0 ? dense_at(ent.tid) : nullptr;
        if (t && (uint64_t)t->data.size() == c1) {
          t->push_grad(floats.data(), (int64_t)c1);
          ok = reply(fd, 0, nullptr, 0);
        }
        break;
      }
      case 3: {  // PULL_SPARSE
        SparseTable* t = ent.kind == 1 ? sparse_at(ent.tid) : nullptr;
        if (t && c1 <= kMaxElems / (uint64_t)t->dim) {
          out.resize(c1 * t->dim);
          t->pull(ids.data(), (int64_t)c1, out.data());
          ok = reply(fd, 0, out.data(), out.size());
        }
        break;
      }
      case 4: {  // PUSH_SPARSE
        SparseTable* t = ent.kind == 1 ? sparse_at(ent.tid) : nullptr;
        if (t && c2 == c1 * (uint64_t)t->dim) {
          t->push_grad(ids.data(), (int64_t)c1, floats.data());
          ok = reply(fd, 0, nullptr, 0);
        }
        break;
      }
      case 5: {  // INIT_DENSE
        DenseTable* t = ent.kind == 0 ? dense_at(ent.tid) : nullptr;
        if (t) {
          t->init(floats.data(), (int64_t)c1);
          ok = reply(fd, 0, nullptr, 0);
        }
        break;
      }
      case 6: {  // PUSH_DELTA (GEO-SGD: param += delta, no optimizer)
        DenseTable* t = ent.kind == 0 ? dense_at(ent.tid) : nullptr;
        if (t && (uint64_t)t->data.size() == c1) {
          std::lock_guard<std::mutex> g(t->mu_);
          for (uint64_t i = 0; i < c1; ++i) t->data[i] += floats[i];
          ok = reply(fd, 0, nullptr, 0);
        }
        break;
      }
      default:
        break;
    }
    if (!ok && !reply(fd, 1, nullptr, 0)) break;
  }
  ::close(fd);
}

void handle_conn(int fd) {
  // a detached thread must never let an exception escape
  // (std::terminate would abort the whole PS process)
  try {
    handle_conn_impl(fd);
  } catch (...) {
    ::close(fd);
  }
}

void accept_loop(Listener* L) {
  for (;;) {
    int fd = ::accept(L->fd, nullptr, nullptr);
    if (fd < 0) {
      if (L->stop.load() || errno == EBADF || errno == EINVAL) break;
      ::usleep(10000);  // transient (EMFILE/EINTR): back off, no spin
      continue;
    }
    if (L->stop.load()) {
      ::close(fd);
      break;
    }
    std::thread(handle_conn, fd).detach();
  }
  // the accept thread owns the close; fd_mu keeps the stop thread's
  // shutdown() from landing on a reused fd number after this close
  std::lock_guard<std::mutex> g(L->fd_mu);
  ::close(L->fd);
  L->closed = true;
}

}  // namespace

void ps_bind_name(const char* name, int32_t kind, int32_t tid) {
  std::lock_guard<std::mutex> g(g_names_mu);
  g_names[std::string(name)] = NameEntry{kind, tid};
}

int32_t ps_serve_start(const char* host, int32_t port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  addr.sin_addr.s_addr = host && *host ? inet_addr(host) : htonl(INADDR_ANY);
  if (::bind(fd, (sockaddr*)&addr, sizeof(addr)) < 0 ||
      ::listen(fd, 64) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, (sockaddr*)&addr, &len);
  auto* L = new Listener();
  L->fd = fd;
  L->port = (int)ntohs(addr.sin_port);
  {
    std::lock_guard<std::mutex> g(g_listeners_mu);
    g_listeners.push_back(L);
  }
  std::thread(accept_loop, L).detach();
  return (int32_t)L->port;
}

// stop one listener by its bound port; port <= 0 stops them all.
// Listener structs are parked (never freed): the detached accept thread
// may still be reading its stop flag.
void ps_serve_stop_port(int32_t port) {
  std::lock_guard<std::mutex> g(g_listeners_mu);
  for (Listener* L : g_listeners) {
    if (L->stop.load()) continue;
    if (port > 0 && L->port != port) continue;
    L->stop.store(true);
    // shutdown() only — wakes the parked accept(); the accept thread
    // owns the close().  fd_mu + closed make the two orderings safe:
    // closing here (or shutting down after the accept thread already
    // closed) would race kernel fd reuse and hit an unrelated socket.
    std::lock_guard<std::mutex> fg(L->fd_mu);
    if (!L->closed) ::shutdown(L->fd, SHUT_RDWR);
  }
}

void ps_serve_stop() { ps_serve_stop_port(0); }

void ps_reset_all() {
  // Tables are parked, not deleted: a server handler thread may still be
  // inside a pull/push through a pointer copied by dense_at/sparse_at,
  // so freeing here would be a use-after-free.  reset is a test/teardown
  // API; the parked tables' memory is reclaimed at process exit.
  static std::vector<DenseTable*> dense_graveyard;
  static std::vector<SparseTable*> sparse_graveyard;
  std::lock_guard<std::mutex> g(g_mu);
  dense_graveyard.insert(dense_graveyard.end(), g_dense.begin(), g_dense.end());
  sparse_graveyard.insert(sparse_graveyard.end(), g_sparse.begin(),
                          g_sparse.end());
  g_dense.clear();
  g_sparse.clear();
}

}  // extern "C"
