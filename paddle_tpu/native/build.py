"""On-demand native builds: g++ -O3 -shared -fPIC, cached by source hash.

The reference ships its C++ prebuilt via cmake (reference: cmake/);
here native components compile on first use and cache under
~/.cache/paddle_tpu/native/.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_CACHE_DIR = os.path.expanduser("~/.cache/paddle_tpu/native")
_LOCK = threading.Lock()
_LOADED = {}


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if needed) and dlopen native/<name>.cpp."""
    with _LOCK:
        if name in _LOADED:
            return _LOADED[name]
        src = os.path.join(os.path.dirname(__file__), name + ".cpp")
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        os.makedirs(_CACHE_DIR, exist_ok=True)
        so_path = os.path.join(_CACHE_DIR, f"{name}-{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + ".tmp"
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                   "-o", tmp, src, "-lpthread"]
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp, so_path)
        lib = ctypes.CDLL(so_path)
        _LOADED[name] = lib
        return lib
