"""On-demand native builds: g++ -O3 -shared -fPIC, cached by source hash.

The reference ships its C++ prebuilt via cmake (reference: cmake/);
here native components compile on first use and cache under
~/.cache/paddle_tpu/native/.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_CACHE_DIR = os.path.expanduser("~/.cache/paddle_tpu/native")
_LOCK = threading.Lock()
_LOADED = {}


def _tf_include_dir():
    """The PJRT C API headers ship with the installed tensorflow wheel
    (xla/pjrt/c/pjrt_c_api.h) — public vendored headers, not reference
    code."""
    import importlib.util

    spec = importlib.util.find_spec("tensorflow")
    if spec is None or not spec.submodule_search_locations:
        return None
    return os.path.join(spec.submodule_search_locations[0], "include")


# per-library extra compile/link flags
EXTRA_FLAGS = {
    "predictor_capi": lambda: (
        [f"-I{_tf_include_dir()}"] if _tf_include_dir() else []
    ) + ["-ldl"],
}


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if needed) and dlopen native/<name>.cpp."""
    with _LOCK:
        if name in _LOADED:
            return _LOADED[name]
        here = os.path.dirname(__file__)
        src = os.path.join(here, name + ".cpp")
        h = hashlib.sha256()
        with open(src, "rb") as f:
            h.update(f.read())
        # locally included headers + extra flags are part of the ABI:
        # hash them too so edits rebuild instead of loading a stale .so
        for hdr in sorted(os.listdir(here)):
            if hdr.endswith(".h"):
                with open(os.path.join(here, hdr), "rb") as f:
                    h.update(f.read())
        extra0 = EXTRA_FLAGS.get(name)
        h.update(repr(extra0() if callable(extra0) else extra0).encode())
        digest = h.hexdigest()[:16]
        os.makedirs(_CACHE_DIR, exist_ok=True)
        so_path = os.path.join(_CACHE_DIR, f"{name}-{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + ".tmp"
            extra = EXTRA_FLAGS.get(name)
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                   "-o", tmp, src, "-lpthread"] + \
                  (extra() if callable(extra) else list(extra or []))
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"native build of {name} failed:\n$ {' '.join(cmd)}\n"
                    f"{proc.stderr[-4000:]}")
            os.replace(tmp, so_path)
        lib = ctypes.CDLL(so_path)
        _LOADED[name] = lib
        return lib
