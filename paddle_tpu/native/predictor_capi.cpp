// Native serving runtime: StableHLO + PTW weights -> PJRT C API.
//
// Reference analog: paddle/fluid/inference/capi/c_api.cc +
// api/analysis_predictor.cc — the native no-Python serving path.  On
// TPU the "engine" is the PJRT plugin (libtpu.so): we dlopen it, build
// a client, compile the exported StableHLO module once, stage weights
// on device, and per Run() stage inputs, execute, and read back
// outputs.  The PJRT C API is ABI-stable (struct_size-versioned), so
// this binary keeps working across plugin updates.
//
// Artifact layout: see paddle_tpu/inference/export.py.

#include "pd_inference_c_api.h"

#include <dlfcn.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

std::string pjrt_error_message(const PJRT_Api* api, PJRT_Error* err) {
  PJRT_Error_Message_Args margs;
  std::memset(&margs, 0, sizeof(margs));
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = err;
  api->PJRT_Error_Message(&margs);
  std::string msg(margs.message, margs.message_size);
  PJRT_Error_Destroy_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = err;
  api->PJRT_Error_Destroy(&dargs);
  return msg;
}

// RETURN_IF_PJRT_ERROR: capture + free the error, set g_last_error.
#define PD_CHECK_PJRT(api, expr, cleanup)                       \
  do {                                                          \
    PJRT_Error* _err = (expr);                                  \
    if (_err != nullptr) {                                      \
      set_error(std::string(#expr) + ": " +                     \
                pjrt_error_message((api), _err));               \
      cleanup;                                                  \
    }                                                           \
  } while (0)

int64_t dtype_size(int32_t code) {
  switch (code) {
    case PD_FLOAT64:
      return 8;
    case PD_INT64:
      return 8;
    case PD_FLOAT32:
      return 4;
    case PD_INT32:
      return 4;
    case PD_BFLOAT16:
      return 2;
    case PD_FLOAT16:
      return 2;
    default:
      return 1;
  }
}

bool dtype_to_pjrt(int32_t code, PJRT_Buffer_Type* out) {
  switch (code) {
    case PD_FLOAT32:
      *out = PJRT_Buffer_Type_F32;
      return true;
    case PD_FLOAT64:
      *out = PJRT_Buffer_Type_F64;
      return true;
    case PD_INT32:
      *out = PJRT_Buffer_Type_S32;
      return true;
    case PD_INT64:
      *out = PJRT_Buffer_Type_S64;
      return true;
    case PD_BFLOAT16:
      *out = PJRT_Buffer_Type_BF16;
      return true;
    case PD_FLOAT16:
      *out = PJRT_Buffer_Type_F16;
      return true;
    case PD_UINT8:
      *out = PJRT_Buffer_Type_U8;
      return true;
    case PD_INT8:
      *out = PJRT_Buffer_Type_S8;
      return true;
    case PD_BOOL:
      *out = PJRT_Buffer_Type_PRED;
      return true;
    default:
      return false;
  }
}

bool pjrt_to_dtype(PJRT_Buffer_Type t, int32_t* out) {
  switch (t) {
    case PJRT_Buffer_Type_F32:
      *out = PD_FLOAT32;
      return true;
    case PJRT_Buffer_Type_F64:
      *out = PD_FLOAT64;
      return true;
    case PJRT_Buffer_Type_S32:
      *out = PD_INT32;
      return true;
    case PJRT_Buffer_Type_S64:
      *out = PD_INT64;
      return true;
    case PJRT_Buffer_Type_BF16:
      *out = PD_BFLOAT16;
      return true;
    case PJRT_Buffer_Type_F16:
      *out = PD_FLOAT16;
      return true;
    case PJRT_Buffer_Type_U8:
      *out = PD_UINT8;
      return true;
    case PJRT_Buffer_Type_S8:
      *out = PD_INT8;
      return true;
    case PJRT_Buffer_Type_PRED:
      *out = PD_BOOL;
      return true;
    default:
      return false;
  }
}

struct HostTensor {
  std::string name;
  int32_t dtype = PD_FLOAT32;
  std::vector<int64_t> dims;
  std::vector<char> data;
};

// PTW1 weights container reader (export.py save_ptw).
bool read_ptw(const std::string& path, std::vector<HostTensor>* out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    set_error("cannot open " + path);
    return false;
  }
  char magic[4];
  f.read(magic, 4);
  if (std::memcmp(magic, "PTW1", 4) != 0) {
    set_error("bad PTW magic in " + path);
    return false;
  }
  uint32_t n = 0;
  f.read(reinterpret_cast<char*>(&n), 4);
  for (uint32_t i = 0; i < n; ++i) {
    HostTensor t;
    uint16_t name_len = 0;
    f.read(reinterpret_cast<char*>(&name_len), 2);
    t.name.resize(name_len);
    f.read(&t.name[0], name_len);
    uint8_t code = 0, ndim = 0;
    f.read(reinterpret_cast<char*>(&code), 1);
    f.read(reinterpret_cast<char*>(&ndim), 1);
    t.dtype = code;
    t.dims.resize(ndim);
    for (int d = 0; d < ndim; ++d) {
      uint32_t dim = 0;
      f.read(reinterpret_cast<char*>(&dim), 4);
      t.dims[d] = dim;
    }
    uint64_t nbytes = 0;
    f.read(reinterpret_cast<char*>(&nbytes), 8);
    if (nbytes > (1ull << 38)) {  // 256 GiB: clearly corrupt metadata
      set_error("implausible tensor size in " + path + " (corrupt file?)");
      return false;
    }
    t.data.resize(nbytes);
    f.read(t.data.data(), static_cast<std::streamsize>(nbytes));
    if (!f) {
      set_error("truncated PTW file " + path);
      return false;
    }
    out->push_back(std::move(t));
  }
  return true;
}

struct MetaInput {
  std::string name;
  int32_t dtype;
  std::vector<int64_t> dims;
};

// meta.txt (export.py): line-oriented, native-friendly.
bool read_meta(const std::string& path, std::vector<MetaInput>* inputs,
               std::vector<std::string>* outputs) {
  std::ifstream f(path);
  if (!f) {
    set_error("cannot open " + path);
    return false;
  }
  std::string tag;
  f >> tag;
  if (tag != "PTMETA1") {
    set_error("bad meta header in " + path);
    return false;
  }
  size_t n = 0;
  f >> tag >> n;  // "inputs N"
  for (size_t i = 0; i < n; ++i) {
    MetaInput mi;
    int ndim = 0;
    f >> mi.name >> mi.dtype >> ndim;
    mi.dims.resize(ndim);
    for (int d = 0; d < ndim; ++d) f >> mi.dims[d];
    inputs->push_back(std::move(mi));
  }
  f >> tag >> n;  // "outputs N"
  for (size_t i = 0; i < n; ++i) {
    std::string name;
    f >> name;
    outputs->push_back(name);
  }
  return static_cast<bool>(f);
}

}  // namespace

struct PD_NativePredictor {
  void* plugin_handle = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  PJRT_LoadedExecutable* executable = nullptr;
  PJRT_Device* device = nullptr;
  size_t num_outputs = 0;
  std::vector<PJRT_Buffer*> weight_buffers;
  std::vector<MetaInput> inputs;
  std::vector<std::string> output_names;

  ~PD_NativePredictor() {
    if (api != nullptr) {
      for (PJRT_Buffer* b : weight_buffers) {
        if (b == nullptr) continue;
        PJRT_Buffer_Destroy_Args args;
        std::memset(&args, 0, sizeof(args));
        args.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
        args.buffer = b;
        PJRT_Error* err = api->PJRT_Buffer_Destroy(&args);
        if (err != nullptr) pjrt_error_message(api, err);
      }
      if (executable != nullptr) {
        PJRT_LoadedExecutable_Destroy_Args args;
        std::memset(&args, 0, sizeof(args));
        args.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
        args.executable = executable;
        PJRT_Error* err = api->PJRT_LoadedExecutable_Destroy(&args);
        if (err != nullptr) pjrt_error_message(api, err);
      }
      if (client != nullptr) {
        PJRT_Client_Destroy_Args args;
        std::memset(&args, 0, sizeof(args));
        args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
        args.client = client;
        PJRT_Error* err = api->PJRT_Client_Destroy(&args);
        if (err != nullptr) pjrt_error_message(api, err);
      }
    }
    // plugin_handle deliberately not dlclose'd: TPU plugins don't
    // support unload/reload in one process.
  }
};

namespace {

bool await_and_destroy_event(const PJRT_Api* api, PJRT_Event* event) {
  if (event == nullptr) return true;
  PJRT_Event_Await_Args aargs;
  std::memset(&aargs, 0, sizeof(aargs));
  aargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aargs.event = event;
  PJRT_Error* err = api->PJRT_Event_Await(&aargs);
  bool ok = true;
  if (err != nullptr) {
    set_error("event: " + pjrt_error_message(api, err));
    ok = false;
  }
  PJRT_Event_Destroy_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  dargs.event = event;
  err = api->PJRT_Event_Destroy(&dargs);
  if (err != nullptr) pjrt_error_message(api, err);
  return ok;
}

PJRT_Buffer* host_to_device(const PJRT_Api* api, PJRT_Client* client,
                            PJRT_Device* device, const void* data,
                            int32_t dtype, const int64_t* dims, int ndim) {
  PJRT_Buffer_Type type;
  if (!dtype_to_pjrt(dtype, &type)) {
    set_error("unsupported dtype code " + std::to_string(dtype));
    return nullptr;
  }
  PJRT_Client_BufferFromHostBuffer_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
  args.client = client;
  args.data = data;
  args.type = type;
  args.dims = dims;
  args.num_dims = static_cast<size_t>(ndim);
  args.host_buffer_semantics =
      PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
  args.device = device;
  PD_CHECK_PJRT(api, api->PJRT_Client_BufferFromHostBuffer(&args),
                return nullptr);
  if (!await_and_destroy_event(api, args.done_with_host_buffer)) {
    return nullptr;
  }
  return args.buffer;
}

void destroy_buffer(const PJRT_Api* api, PJRT_Buffer* b) {
  if (b == nullptr) return;
  PJRT_Buffer_Destroy_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
  args.buffer = b;
  PJRT_Error* err = api->PJRT_Buffer_Destroy(&args);
  if (err != nullptr) pjrt_error_message(api, err);
}

}  // namespace

extern "C" {

namespace {

struct NamedOption {
  std::string name;
  bool is_int;
  std::string str_value;
  int64_t int_value;
};

// "<name> int <v>" / "<name> str <v>" lines -> PJRT_NamedValue inputs.
std::vector<NamedOption> parse_options(const char* options) {
  std::vector<NamedOption> out;
  if (options == nullptr) return out;
  std::stringstream ss(options);
  std::string line;
  while (std::getline(ss, line)) {
    if (line.empty()) continue;
    std::stringstream ls(line);
    NamedOption opt;
    std::string type;
    ls >> opt.name >> type;
    if (type == "int") {
      ls >> opt.int_value;
      opt.is_int = true;
    } else {
      std::getline(ls, opt.str_value);
      // strip the single separating space
      if (!opt.str_value.empty() && opt.str_value[0] == ' ') {
        opt.str_value.erase(0, 1);
      }
      opt.is_int = false;
    }
    out.push_back(std::move(opt));
  }
  return out;
}

}  // namespace

namespace {
PD_NativePredictor* create_impl(const char* export_dir,
                                const char* plugin_path,
                                const char* options);
}

PD_NativePredictor* PD_NativePredictorCreate(const char* export_dir,
                                             const char* plugin_path,
                                             const char* options) {
  // no exception may cross the C boundary (ctypes/Go callers)
  try {
    return create_impl(export_dir, plugin_path, options);
  } catch (const std::exception& e) {
    set_error(std::string("internal error: ") + e.what());
    return nullptr;
  } catch (...) {
    set_error("internal error (unknown exception)");
    return nullptr;
  }
}

namespace {
PD_NativePredictor* create_impl(const char* export_dir,
                                const char* plugin_path,
                                const char* options) {
  auto pred = std::make_unique<PD_NativePredictor>();
  std::string dir(export_dir);

  // 1. plugin
  pred->plugin_handle = dlopen(plugin_path, RTLD_NOW | RTLD_LOCAL);
  if (pred->plugin_handle == nullptr) {
    set_error(std::string("dlopen failed: ") + dlerror());
    return nullptr;
  }
  using GetPjrtApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetPjrtApiFn>(
      dlsym(pred->plugin_handle, "GetPjrtApi"));
  if (get_api == nullptr) {
    set_error(std::string(plugin_path) + " exports no GetPjrtApi symbol");
    return nullptr;
  }
  pred->api = get_api();
  const PJRT_Api* api = pred->api;
  if (api->pjrt_api_version.major_version != PJRT_API_MAJOR) {
    set_error("PJRT ABI major mismatch: plugin " +
              std::to_string(api->pjrt_api_version.major_version) +
              " vs built-against " + std::to_string(PJRT_API_MAJOR));
    return nullptr;
  }

  {
    PJRT_Plugin_Initialize_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    PD_CHECK_PJRT(api, api->PJRT_Plugin_Initialize(&args), return nullptr);
  }

  // 2. client + device
  {
    std::vector<NamedOption> opts = parse_options(options);
    std::vector<PJRT_NamedValue> named(opts.size());
    for (size_t i = 0; i < opts.size(); ++i) {
      std::memset(&named[i], 0, sizeof(PJRT_NamedValue));
      named[i].struct_size = PJRT_NamedValue_STRUCT_SIZE;
      named[i].name = opts[i].name.c_str();
      named[i].name_size = opts[i].name.size();
      if (opts[i].is_int) {
        named[i].type = PJRT_NamedValue_kInt64;
        named[i].int64_value = opts[i].int_value;
        named[i].value_size = 1;
      } else {
        named[i].type = PJRT_NamedValue_kString;
        named[i].string_value = opts[i].str_value.c_str();
        named[i].value_size = opts[i].str_value.size();
      }
    }
    PJRT_Client_Create_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
    args.create_options = named.empty() ? nullptr : named.data();
    args.num_options = named.size();
    PD_CHECK_PJRT(api, api->PJRT_Client_Create(&args), return nullptr);
    pred->client = args.client;
  }
  {
    PJRT_Client_AddressableDevices_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
    args.client = pred->client;
    PD_CHECK_PJRT(api, api->PJRT_Client_AddressableDevices(&args),
                  return nullptr);
    if (args.num_addressable_devices == 0) {
      set_error("no addressable devices");
      return nullptr;
    }
    pred->device = args.addressable_devices[0];
  }

  // 3. compile the StableHLO module
  {
    std::ifstream f(dir + "/model.stablehlo.mlir");
    if (!f) {
      set_error("cannot open " + dir + "/model.stablehlo.mlir");
      return nullptr;
    }
    std::stringstream ss;
    ss << f.rdbuf();
    std::string code = ss.str();

    PJRT_Program program;
    std::memset(&program, 0, sizeof(program));
    program.struct_size = PJRT_Program_STRUCT_SIZE;
    program.code = code.data();
    program.code_size = code.size();
    static const char kFormat[] = "mlir";
    program.format = kFormat;
    program.format_size = sizeof(kFormat) - 1;

    // Minimal serialized xla CompileOptionsProto:
    // executable_build_options { num_replicas: 1  num_partitions: 1 }
    // (field 3 LEN { field 4 varint 1, field 5 varint 1 })
    static const char kCompileOptions[] = {0x1A, 0x04, 0x20, 0x01,
                                           0x28, 0x01};

    PJRT_Client_Compile_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
    args.client = pred->client;
    args.program = &program;
    args.compile_options = kCompileOptions;
    args.compile_options_size = sizeof(kCompileOptions);
    PD_CHECK_PJRT(api, api->PJRT_Client_Compile(&args), return nullptr);
    pred->executable = args.executable;
  }

  // number of outputs (via the underlying PJRT_Executable)
  {
    PJRT_LoadedExecutable_GetExecutable_Args gargs;
    std::memset(&gargs, 0, sizeof(gargs));
    gargs.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
    gargs.loaded_executable = pred->executable;
    PD_CHECK_PJRT(api, api->PJRT_LoadedExecutable_GetExecutable(&gargs),
                  return nullptr);
    PJRT_Executable_NumOutputs_Args nargs;
    std::memset(&nargs, 0, sizeof(nargs));
    nargs.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
    nargs.executable = gargs.executable;
    PD_CHECK_PJRT(api, api->PJRT_Executable_NumOutputs(&nargs),
                  return nullptr);
    pred->num_outputs = nargs.num_outputs;
  }

  // 4. meta + weights staged to device once
  if (!read_meta(dir + "/meta.txt", &pred->inputs, &pred->output_names)) {
    return nullptr;
  }
  std::vector<HostTensor> weights;
  if (!read_ptw(dir + "/weights.ptw", &weights)) return nullptr;
  for (const HostTensor& w : weights) {
    PJRT_Buffer* buf =
        host_to_device(api, pred->client, pred->device, w.data.data(),
                       w.dtype, w.dims.data(), static_cast<int>(w.dims.size()));
    if (buf == nullptr) return nullptr;
    pred->weight_buffers.push_back(buf);
  }
  return pred.release();
}
}  // namespace

int PD_NativePredictorNumInputs(PD_NativePredictor* p) {
  return static_cast<int>(p->inputs.size());
}

int PD_NativePredictorNumOutputs(PD_NativePredictor* p) {
  return static_cast<int>(p->output_names.size());
}

const char* PD_NativePredictorInputName(PD_NativePredictor* p, int i) {
  if (i < 0 || i >= static_cast<int>(p->inputs.size())) return nullptr;
  return p->inputs[static_cast<size_t>(i)].name.c_str();
}

const char* PD_NativePredictorOutputName(PD_NativePredictor* p, int i) {
  if (i < 0 || i >= static_cast<int>(p->output_names.size())) return nullptr;
  return p->output_names[static_cast<size_t>(i)].c_str();
}

int PD_NativePredictorInputInfo(PD_NativePredictor* p, int i,
                                PD_NativeTensor* info) {
  if (i < 0 || i >= static_cast<int>(p->inputs.size())) return -1;
  const MetaInput& mi = p->inputs[static_cast<size_t>(i)];
  info->dtype = mi.dtype;
  info->ndim = static_cast<int32_t>(mi.dims.size());
  for (size_t d = 0; d < mi.dims.size() && d < PD_MAX_RANK; ++d)
    info->dims[d] = mi.dims[d];
  return 0;
}

namespace {
int run_impl(PD_NativePredictor* p, const PD_NativeTensor* ins, int n_in,
             PD_NativeTensor* outs, int max_out);
}

int PD_NativePredictorRun(PD_NativePredictor* p, const PD_NativeTensor* ins,
                          int n_in, PD_NativeTensor* outs, int max_out) {
  try {
    return run_impl(p, ins, n_in, outs, max_out);
  } catch (const std::exception& e) {
    set_error(std::string("internal error: ") + e.what());
    return -1;
  } catch (...) {
    set_error("internal error (unknown exception)");
    return -1;
  }
}

namespace {
int run_impl(PD_NativePredictor* p, const PD_NativeTensor* ins, int n_in,
             PD_NativeTensor* outs, int max_out) {
  const PJRT_Api* api = p->api;
  if (n_in != static_cast<int>(p->inputs.size())) {
    set_error("expected " + std::to_string(p->inputs.size()) + " inputs, got " +
              std::to_string(n_in));
    return -1;
  }

  // stage inputs
  std::vector<PJRT_Buffer*> input_buffers;
  auto cleanup_inputs = [&]() {
    for (PJRT_Buffer* b : input_buffers) destroy_buffer(api, b);
  };
  for (int i = 0; i < n_in; ++i) {
    const PD_NativeTensor& t = ins[i];
    PJRT_Buffer* buf = host_to_device(api, p->client, p->device, t.data,
                                      t.dtype, t.dims, t.ndim);
    if (buf == nullptr) {
      cleanup_inputs();
      return -1;
    }
    input_buffers.push_back(buf);
  }

  // argument list: weights then inputs (export.py call convention)
  std::vector<PJRT_Buffer*> args_row;
  args_row.reserve(p->weight_buffers.size() + input_buffers.size());
  for (PJRT_Buffer* b : p->weight_buffers) args_row.push_back(b);
  for (PJRT_Buffer* b : input_buffers) args_row.push_back(b);
  PJRT_Buffer* const* arg_lists[1] = {args_row.data()};

  std::vector<PJRT_Buffer*> out_row(p->num_outputs, nullptr);
  PJRT_Buffer** out_lists[1] = {out_row.data()};
  PJRT_Event* device_complete = nullptr;

  PJRT_ExecuteOptions options;
  std::memset(&options, 0, sizeof(options));
  options.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

  PJRT_LoadedExecutable_Execute_Args eargs;
  std::memset(&eargs, 0, sizeof(eargs));
  eargs.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  eargs.executable = p->executable;
  eargs.options = &options;
  eargs.argument_lists = arg_lists;
  eargs.num_devices = 1;
  eargs.num_args = args_row.size();
  eargs.output_lists = out_lists;
  eargs.device_complete_events = &device_complete;
  PD_CHECK_PJRT(api, api->PJRT_LoadedExecutable_Execute(&eargs), {
    cleanup_inputs();
    return -1;
  });
  if (!await_and_destroy_event(api, device_complete)) {
    cleanup_inputs();
    for (PJRT_Buffer* b : out_row) destroy_buffer(api, b);
    return -1;
  }
  cleanup_inputs();

  // read outputs back.  NOTE: PD_CHECK_PJRT's cleanup runs inside the
  // macro's do-while, so `continue`/`break` must not be used there —
  // this helper uses real returns and does NOT destroy `b` (the caller
  // owns it on every path).
  auto read_output = [api](PJRT_Buffer* b, PD_NativeTensor* t) -> bool {
    std::memset(t, 0, sizeof(*t));

    PJRT_Buffer_ElementType_Args targs;
    std::memset(&targs, 0, sizeof(targs));
    targs.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
    targs.buffer = b;
    PD_CHECK_PJRT(api, api->PJRT_Buffer_ElementType(&targs), return false);
    if (!pjrt_to_dtype(targs.type, &t->dtype)) {
      set_error("unsupported output element type");
      return false;
    }

    PJRT_Buffer_Dimensions_Args dargs;
    std::memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
    dargs.buffer = b;
    PD_CHECK_PJRT(api, api->PJRT_Buffer_Dimensions(&dargs), return false);
    t->ndim = static_cast<int32_t>(dargs.num_dims);
    if (t->ndim > PD_MAX_RANK) {
      set_error("output rank > PD_MAX_RANK");
      return false;
    }
    for (int d = 0; d < t->ndim; ++d) t->dims[d] = dargs.dims[d];

    PJRT_Buffer_ToHostBuffer_Args hargs;
    std::memset(&hargs, 0, sizeof(hargs));
    hargs.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    hargs.src = b;
    PD_CHECK_PJRT(api, api->PJRT_Buffer_ToHostBuffer(&hargs), return false);
    t->nbytes = hargs.dst_size;
    t->data = std::malloc(t->nbytes);
    if (t->data == nullptr) {
      set_error("out of host memory for output buffer");
      return false;
    }
    hargs.dst = t->data;
    bool ok = true;
    PJRT_Error* err = api->PJRT_Buffer_ToHostBuffer(&hargs);
    if (err != nullptr) {
      set_error("PJRT_Buffer_ToHostBuffer: " + pjrt_error_message(api, err));
      ok = false;
    } else if (!await_and_destroy_event(api, hargs.event)) {
      ok = false;
    }
    if (!ok) {
      std::free(t->data);
      t->data = nullptr;
    }
    return ok;
  };

  int n_out = static_cast<int>(p->num_outputs);
  int filled = 0;
  bool failed = false;
  for (int i = 0; i < n_out; ++i) {
    PJRT_Buffer* b = out_row[static_cast<size_t>(i)];
    if (i < max_out && !failed) {
      if (read_output(b, &outs[i])) {
        ++filled;
      } else {
        failed = true;
      }
    }
    destroy_buffer(api, b);
  }
  if (failed) {
    for (int i = 0; i < filled; ++i) PD_NativeTensorFree(&outs[i]);
    return -1;
  }
  return filled;
}
}  // namespace

void PD_NativeTensorFree(PD_NativeTensor* t) {
  if (t != nullptr && t->data != nullptr) {
    std::free(t->data);
    t->data = nullptr;
    t->nbytes = 0;
  }
}

void PD_NativePredictorDestroy(PD_NativePredictor* p) { delete p; }

const char* PD_NativeLastError(void) { return g_last_error.c_str(); }

}  // extern "C"
