"""Native (C++) runtime components, built on demand with the system
toolchain (g++) and loaded via ctypes — the TPU-native counterpart of the
reference's C++ runtime libraries (SURVEY.md §2.9)."""
from .build import load_library
