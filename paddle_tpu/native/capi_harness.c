/* C harness for the predictor C ABI the Go binding calls.
 *
 * dlopens predictor_capi.so and drives the EXACT call sequence
 * go/paddle/predictor.go makes (Create -> NumInputs/Outputs ->
 * Input/OutputName -> InputInfo (incl. out-of-range) -> Run -> Free/
 * Destroy), including the zero-input and zero-output pointer shapes
 * the cgo layer produces (NULL tensor arrays).  This is the CI-run
 * stand-in for a Go toolchain (VERDICT r4 Weak #5): if the struct
 * layout or a symbol drifts from pd_inference_c_api.h, this harness
 * breaks the same way cgo would.
 *
 * Usage:
 *   capi_harness <libpredictor_capi.so> err
 *       exercise symbol resolution + the error path (no device needed)
 *   capi_harness <libpredictor_capi.so> run <export_dir> <plugin.so>
 *       full inference sequence against a real PJRT plugin
 */
#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define PD_MAX_RANK 8
typedef struct PD_NativeTensor {
  int32_t dtype;
  int32_t ndim;
  int64_t dims[PD_MAX_RANK];
  void* data;
  size_t nbytes;
} PD_NativeTensor;

typedef struct PD_NativePredictor PD_NativePredictor;

typedef PD_NativePredictor* (*create_fn)(const char*, const char*,
                                         const char*);
typedef int (*num_fn)(PD_NativePredictor*);
typedef const char* (*name_fn)(PD_NativePredictor*, int);
typedef int (*info_fn)(PD_NativePredictor*, int, PD_NativeTensor*);
typedef int (*run_fn)(PD_NativePredictor*, const PD_NativeTensor*, int,
                      PD_NativeTensor*, int);
typedef void (*tfree_fn)(PD_NativeTensor*);
typedef void (*destroy_fn)(PD_NativePredictor*);
typedef const char* (*err_fn)(void);

#define DIE(msg)                                        \
  do {                                                  \
    fprintf(stderr, "FAIL: %s\n", msg);                 \
    return 1;                                           \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 3) DIE("usage: capi_harness <so> err|run [export_dir plugin]");
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_LOCAL);
  if (!lib) {
    fprintf(stderr, "FAIL: dlopen: %s\n", dlerror());
    return 1;
  }
  /* resolve every symbol the Go binding references */
  create_fn create = (create_fn)dlsym(lib, "PD_NativePredictorCreate");
  num_fn num_in = (num_fn)dlsym(lib, "PD_NativePredictorNumInputs");
  num_fn num_out = (num_fn)dlsym(lib, "PD_NativePredictorNumOutputs");
  name_fn in_name = (name_fn)dlsym(lib, "PD_NativePredictorInputName");
  name_fn out_name = (name_fn)dlsym(lib, "PD_NativePredictorOutputName");
  info_fn info = (info_fn)dlsym(lib, "PD_NativePredictorInputInfo");
  run_fn run = (run_fn)dlsym(lib, "PD_NativePredictorRun");
  tfree_fn tfree = (tfree_fn)dlsym(lib, "PD_NativeTensorFree");
  destroy_fn destroy = (destroy_fn)dlsym(lib, "PD_NativePredictorDestroy");
  err_fn last_err = (err_fn)dlsym(lib, "PD_NativeLastError");
  if (!create || !num_in || !num_out || !in_name || !out_name || !info ||
      !run || !tfree || !destroy || !last_err)
    DIE("missing C API symbol");
  printf("symbols: OK\n");

  if (strcmp(argv[2], "err") == 0) {
    /* the error path Go hits when the plugin can't be opened */
    PD_NativePredictor* p =
        create("/nonexistent/export", "/nonexistent/plugin.so", "");
    if (p != NULL) DIE("create with bogus plugin should return NULL");
    const char* e = last_err();
    if (!e || !*e) DIE("PD_NativeLastError empty after failed create");
    printf("error path: OK (%s)\n", e);
    return 0;
  }

  if (argc < 5) DIE("run mode needs <export_dir> <plugin.so> [options]");
  PD_NativePredictor* p = create(argv[3], argv[4], argc > 5 ? argv[5] : "");
  if (!p) {
    fprintf(stderr, "FAIL: create: %s\n", last_err());
    return 1;
  }
  int ni = num_in(p), no = num_out(p);
  printf("inputs=%d outputs=%d\n", ni, no);
  if (ni < 0 || no < 0) DIE("negative arity");
  for (int i = 0; i < ni; ++i)
    printf("  in[%d] = %s\n", i, in_name(p, i));
  for (int i = 0; i < no; ++i)
    printf("  out[%d] = %s\n", i, out_name(p, i));

  PD_NativeTensor oob;
  if (info(p, ni + 3, &oob) != -1) DIE("InputInfo out-of-range must be -1");

  /* build inputs exactly like go Tensor.toC: info -> alloc -> fill */
  PD_NativeTensor* ins = calloc(ni ? ni : 1, sizeof(PD_NativeTensor));
  for (int i = 0; i < ni; ++i) {
    if (info(p, i, &ins[i]) != 0) DIE("InputInfo failed");
    size_t n = 1;
    for (int d = 0; d < ins[i].ndim; ++d) {
      if (ins[i].dims[d] < 0) ins[i].dims[d] = 2; /* dynamic batch */
      n *= (size_t)ins[i].dims[d];
    }
    size_t esz = (ins[i].dtype == 3 || ins[i].dtype == 1) ? 8
                 : (ins[i].dtype == 4 || ins[i].dtype == 5) ? 2
                 : (ins[i].dtype == 6 || ins[i].dtype == 7 ||
                    ins[i].dtype == 8) ? 1 : 4;
    ins[i].nbytes = n * esz;
    ins[i].data = calloc(1, ins[i].nbytes);
    if (ins[i].dtype == 0) { /* f32: deterministic ramp */
      float* f = (float*)ins[i].data;
      for (size_t k = 0; k < n; ++k) f[k] = (float)(k % 7) * 0.25f;
    }
  }

  /* zero-output probe first: Go passes a NULL out pointer then */
  int rc0 = run(p, ni ? ins : NULL, ni, NULL, 0);
  printf("run(max_out=0) -> %d\n", rc0);
  if (rc0 < 0) {
    fprintf(stderr, "FAIL: zero-output run: %s\n", last_err());
    return 1;
  }

  PD_NativeTensor* outs = calloc(no ? no : 1, sizeof(PD_NativeTensor));
  int got = run(p, ni ? ins : NULL, ni, no ? outs : NULL, no);
  if (got < 0) {
    fprintf(stderr, "FAIL: run: %s\n", last_err());
    return 1;
  }
  printf("run -> %d outputs\n", got);
  for (int i = 0; i < got && i < no; ++i) {
    printf("  out[%d]: dtype=%d ndim=%d nbytes=%zu\n", i, outs[i].dtype,
           outs[i].ndim, outs[i].nbytes);
    if (!outs[i].data || outs[i].nbytes == 0) DIE("empty output buffer");
    tfree(&outs[i]);
  }

  /* wrong-arity call must fail cleanly, not crash (cgo error path) */
  if (ni > 0 && run(p, ins, ni - 1, NULL, 0) != -1)
    DIE("wrong input arity must return -1");

  for (int i = 0; i < ni; ++i) free(ins[i].data);
  free(ins);
  free(outs);
  destroy(p);
  printf("C ABI harness: OK\n");
  return 0;
}
