// Native multi-slot data-feed parser.
//
// Capability parity with the reference's C++ DataFeed
// (reference: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed /
// InMemoryDataFeed — 6k LoC of hot-loop text parsing feeding trainer
// threads).  The TPU build keeps ingestion on the host CPU; this parser
// turns multi-slot text ("<n> v1..vn" per slot, slots concatenated per
// line) into flat columnar buffers the Python Dataset batches from.
//
// Text format per record (one line):
//   for each slot in order: <count> <value>*count
// Sparse slots hold int64 feasigns, dense slots hold floats.
//
// Two-phase C ABI (no allocation handoff across the boundary for data —
// caller allocates from the counts returned by phase 1):
//   msf_count(buf, len, nslot) -> n_records, fills per-slot value totals
//   msf_fill(...)              -> writes per-record lengths + flat values
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Phase 1: count records and per-slot total value counts.
// Returns number of records (lines with at least one token), or -1 on a
// malformed line (truncated slot). slot_totals must hold nslot entries.
int64_t msf_count(const char* buf, int64_t len, int32_t nslot,
                  int64_t* slot_totals) {
  for (int32_t s = 0; s < nslot; ++s) slot_totals[s] = 0;
  int64_t nrec = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    // skip blank lines
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    bool ok = true;
    const char* q = p;
    for (int32_t s = 0; s < nslot && ok; ++s) {
      while (q < line_end && (*q == ' ' || *q == '\t')) ++q;
      if (q >= line_end) { ok = false; break; }  // missing trailing slots
      char* next = nullptr;
      long long cnt = strtoll(q, &next, 10);
      if (next == q || next > line_end || cnt < 0) { ok = false; break; }
      q = next;
      for (long long i = 0; i < cnt; ++i) {
        // values may be ints or floats; strtod consumes both
        double v = strtod(q, &next);
        (void)v;
        if (next == q || next > line_end) { ok = false; break; }
        q = next;
      }
      if (ok) slot_totals[s] += cnt;
    }
    if (!ok) return -1;
    ++nrec;
    p = line_end < end ? line_end + 1 : end;
  }
  return nrec;
}

// Phase 2: fill caller-allocated buffers.
//   lens[s]  : int64[n_records]   per-record value count of slot s
//   ivals[s] : int64[totals[s]]   flat values if is_sparse[s]
//   fvals[s] : float[totals[s]]   flat values if !is_sparse[s]
// (only the matching one of ivals/fvals is consulted per slot; the other
// entry may be null.)  Returns n_records or -1 on malformed input.
int64_t msf_fill(const char* buf, int64_t len, int32_t nslot,
                 const int8_t* is_sparse, int64_t** lens, int64_t** ivals,
                 float** fvals) {
  int64_t* pos = static_cast<int64_t*>(calloc(nslot, sizeof(int64_t)));
  int64_t nrec = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    const char* q = p;
    for (int32_t s = 0; s < nslot; ++s) {
      while (q < line_end && (*q == ' ' || *q == '\t')) ++q;
      if (q >= line_end) { free(pos); return -1; }
      char* next = nullptr;
      long long cnt = strtoll(q, &next, 10);
      if (next == q || next > line_end || cnt < 0) { free(pos); return -1; }
      q = next;
      lens[s][nrec] = cnt;
      if (is_sparse[s]) {
        for (long long i = 0; i < cnt; ++i) {
          long long v = strtoll(q, &next, 10);
          if (next == q || next > line_end) { free(pos); return -1; }
          ivals[s][pos[s] + i] = v;
          q = next;
        }
      } else {
        for (long long i = 0; i < cnt; ++i) {
          float v = strtof(q, &next);
          if (next == q || next > line_end) { free(pos); return -1; }
          fvals[s][pos[s] + i] = v;
          q = next;
        }
      }
      pos[s] += cnt;
    }
    ++nrec;
    p = line_end < end ? line_end + 1 : end;
  }
  free(pos);
  return nrec;
}

}  // extern "C"
