/* C inference API over the PJRT runtime.
 *
 * Reference analog: paddle/fluid/inference/capi/ (PD_Predictor,
 * PD_NewAnalysisConfig, PD_PredictorRun, c_api.cc) — a stable C surface
 * over the native predictor so C/Go/R clients can serve models without
 * Python.  TPU-native shape: the artifact is a StableHLO module +
 * weights container exported by paddle_tpu.inference.export_stablehlo;
 * the engine is any PJRT C-API plugin (libtpu.so on TPU hosts).  No
 * Python, no framework runtime in the serving path — dlopen(plugin),
 * compile, execute.
 */
#ifndef PD_INFERENCE_C_API_H_
#define PD_INFERENCE_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* dtype codes shared with the PTW1 weights container
 * (paddle_tpu/inference/export.py DTYPE_CODES) */
enum PD_DType {
  PD_FLOAT32 = 0,
  PD_FLOAT64 = 1,
  PD_INT32 = 2,
  PD_INT64 = 3,
  PD_BFLOAT16 = 4,
  PD_FLOAT16 = 5,
  PD_UINT8 = 6,
  PD_INT8 = 7,
  PD_BOOL = 8,
};

#define PD_MAX_RANK 8

typedef struct PD_NativeTensor {
  int32_t dtype; /* PD_DType */
  int32_t ndim;
  int64_t dims[PD_MAX_RANK];
  void* data;     /* inputs: caller-owned; outputs: free with
                     PD_NativeTensorFree */
  size_t nbytes;
} PD_NativeTensor;

typedef struct PD_NativePredictor PD_NativePredictor;

/* Load <export_dir>/{model.stablehlo.mlir, weights.ptw, meta.txt},
 * create a PJRT client from `plugin_path` (a PJRT C-API plugin .so,
 * e.g. libtpu.so), compile, and stage the weights on device 0.
 *
 * `options` are plugin create-options (PJRT_NamedValue), newline-
 * separated lines of the form "<name> int <value>" or
 * "<name> str <value>".  Pass NULL/"" for plugins that need none
 * (libtpu on a TPU VM).
 *
 * Returns NULL on failure — see PD_NativeLastError(). */
PD_NativePredictor* PD_NativePredictorCreate(const char* export_dir,
                                             const char* plugin_path,
                                             const char* options);

int PD_NativePredictorNumInputs(PD_NativePredictor*);
int PD_NativePredictorNumOutputs(PD_NativePredictor*);
/* Returned strings are owned by the predictor. */
const char* PD_NativePredictorInputName(PD_NativePredictor*, int i);
const char* PD_NativePredictorOutputName(PD_NativePredictor*, int i);

/* Fill dtype/ndim/dims (data/nbytes untouched) for input i from the
 * export metadata.  Returns 0, or -1 for an out-of-range index. */
int PD_NativePredictorInputInfo(PD_NativePredictor*, int i,
                                PD_NativeTensor* info);

/* Run one inference.  `ins` are given in meta input order.  Fills up to
 * `max_out` entries of `outs` (data malloc'd by the library).  Returns
 * the number of outputs, or -1 on error. */
int PD_NativePredictorRun(PD_NativePredictor*, const PD_NativeTensor* ins,
                          int n_in, PD_NativeTensor* outs, int max_out);

void PD_NativeTensorFree(PD_NativeTensor*);
void PD_NativePredictorDestroy(PD_NativePredictor*);

/* Thread-local message for the last failed call. */
const char* PD_NativeLastError(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* PD_INFERENCE_C_API_H_ */
