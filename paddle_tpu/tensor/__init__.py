"""2.0-preview ``paddle.tensor`` namespace.

Reference: python/paddle/tensor/ (creation.py, math.py, manipulation.py,
logic.py, random.py, search.py, stat.py, linalg.py) — thin functional
layer over the op registry that works in both dygraph (traces eagerly)
and static mode (appends ops), exactly like the reference's
``in_dygraph_mode`` dispatch.  All functions here go through
LayerHelper, which handles that dispatch.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import builtins

from ..framework.core import Variable, in_dygraph_mode
from ..framework.dtype import VarType, convert_dtype
from ..layer_helper import LayerHelper
from .. import layers as _L

__all__: list = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _op(op_type, inputs, attrs=None, n_out=1, out_dtype=None, x=None):
    helper = LayerHelper(op_type)
    ref = x if x is not None else next(
        (v[0] for v in inputs.values() if v), None)
    dtype = out_dtype if out_dtype is not None else (
        ref.dtype if ref is not None else VarType.FP32)
    outs = [helper.create_variable_for_type_inference(dtype)
            for _ in range(n_out)]
    helper.append_op(op_type, inputs=inputs, outputs={"Out": outs},
                     attrs=attrs or {})
    return outs[0] if n_out == 1 else outs


def _unary(op_type, public=None):
    def fn(x, name=None):
        return _op(op_type, {"X": [x]})

    fn.__name__ = public or op_type
    __all__.append(fn.__name__)
    return fn


def _binary(op_type, public=None, attrs=None):
    def fn(x, y, name=None):
        return _op(op_type, {"X": [x], "Y": [y]}, attrs=dict(attrs or {}))

    fn.__name__ = public or op_type
    __all__.append(fn.__name__)
    return fn


# -- creation (reference: paddle/tensor/creation.py) ----------------------
@_export
def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if in_dygraph_mode():
        from ..dygraph.base import to_variable

        arr = np.asarray(data)
        if dtype is not None:
            from ..framework.dtype import to_numpy_dtype

            arr = arr.astype(to_numpy_dtype(convert_dtype(dtype)))
        v = to_variable(arr)
        v.stop_gradient = stop_gradient
        return v
    return _L.assign(np.asarray(data))


@_export
def full(shape, fill_value, dtype="float32", name=None):
    return _L.fill_constant(shape=shape, dtype=dtype, value=fill_value)


@_export
def full_like(x, fill_value, dtype=None, name=None):
    return _op("fill_any_like", {"X": [x]},
               attrs={"value": float(fill_value),
                      "dtype": int(convert_to_vartype(dtype))
                      if dtype is not None else -1})


def convert_to_vartype(dtype):
    return convert_dtype(dtype)


@_export
def zeros(shape, dtype="float32", name=None):
    return _L.zeros(shape, dtype)


@_export
def ones(shape, dtype="float32", name=None):
    return _L.ones(shape, dtype)


@_export
def zeros_like(x, dtype=None, name=None):
    return full_like(x, 0.0, dtype)


@_export
def ones_like(x, dtype=None, name=None):
    return full_like(x, 1.0, dtype)


@_export
def arange(start=0, end=None, step=1, dtype="int64", name=None):
    if end is None:
        start, end = 0, start
    return _L.range_(start, end, step, dtype)


@_export
def linspace(start, stop, num, dtype="float32", name=None):
    return _L.linspace(start, stop, num, dtype)


@_export
def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return _L.eye(num_rows, num_columns, dtype=dtype)


@_export
def diag(x, offset=0, padding_value=0, name=None):
    return _op("diag_v2", {"X": [x]},
               attrs={"offset": offset, "padding_value": padding_value})


@_export
def tril(x, diagonal=0, name=None):
    return _op("tril_triu", {"X": [x]},
               attrs={"diagonal": diagonal, "lower": True})


@_export
def triu(x, diagonal=0, name=None):
    return _op("tril_triu", {"X": [x]},
               attrs={"diagonal": diagonal, "lower": False})


@_export
def meshgrid(*args, **kwargs):
    xs = list(args[0]) if len(args) == 1 and isinstance(
        args[0], (list, tuple)) else list(args)
    helper = LayerHelper("meshgrid")
    outs = [helper.create_variable_for_type_inference(xs[0].dtype)
            for _ in xs]
    helper.append_op("meshgrid", inputs={"X": xs}, outputs={"Out": outs})
    return outs


# -- math (reference: paddle/tensor/math.py) -------------------------------
add = _binary("elementwise_add", "add")
subtract = _binary("elementwise_sub", "subtract")
multiply = _binary("elementwise_mul", "multiply")
divide = _binary("elementwise_div", "divide")
floor_divide = _binary("elementwise_floordiv", "floor_divide")
remainder = _binary("elementwise_mod", "remainder")
mod = remainder
maximum = _binary("elementwise_max", "maximum")
minimum = _binary("elementwise_min", "minimum")

for _name in ("abs", "exp", "expm1", "sqrt", "rsqrt", "square", "sign",
              "sin", "cos", "tan", "sinh", "cosh", "asin", "acos", "atan",
              "tanh", "ceil", "floor", "round", "reciprocal", "erf",
              "log", "log2", "log10", "log1p"):
    globals()[_name] = _unary(_name)


@_export
def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return _op("pow", {"X": [x]}, attrs={"factor": float(y)})
    return _op("elementwise_pow", {"X": [x], "Y": [y]})


def _reduce(op_type, public):
    def fn(x, axis=None, keepdim=False, name=None):
        attrs = {"dim": [axis] if isinstance(axis, int)
                 else (list(axis) if axis is not None else []),
                 "keep_dim": keepdim,
                 "reduce_all": axis is None}
        return _op(op_type, {"X": [x]}, attrs=attrs)

    fn.__name__ = public
    __all__.append(public)
    return fn


sum = _reduce("reduce_sum", "sum")
mean = _reduce("reduce_mean", "mean")
max = _reduce("reduce_max", "max")
min = _reduce("reduce_min", "min")
prod = _reduce("reduce_prod", "prod")
all = _reduce("reduce_all", "all")
any = _reduce("reduce_any", "any")


@_export
def logsumexp(x, axis=None, keepdim=False, name=None):
    attrs = {"axis": [axis] if isinstance(axis, int)
             else (list(axis) if axis is not None else []),
             "keepdim": keepdim, "reduce_all": axis is None}
    return _op("logsumexp", {"X": [x]}, attrs=attrs)


@_export
def clip(x, min=None, max=None, name=None):
    lo = float(min) if min is not None else float(np.finfo(np.float32).min)
    hi = float(max) if max is not None else float(np.finfo(np.float32).max)
    return _L.clip(x, lo, hi)


@_export
def cumsum(x, axis=None, dtype=None, name=None):
    attrs = {"axis": axis if axis is not None else -1,
             "flatten": axis is None}
    return _op("cumsum", {"X": [x]}, attrs=attrs)


@_export
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _op("addmm", {"Input": [input], "X": [x], "Y": [y]},
               attrs={"Beta": float(beta), "Alpha": float(alpha)})


@_export
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _op("trace", {"Input": [x]},
               attrs={"offset": offset, "axis1": axis1, "axis2": axis2})


@_export
def kron(x, y, name=None):
    return _op("kron", {"X": [x], "Y": [y]})


@_export
def isnan(x, name=None):
    return _op("isnan_v2", {"X": [x]}, out_dtype=VarType.BOOL)


@_export
def isinf(x, name=None):
    return _op("isinf_v2", {"X": [x]}, out_dtype=VarType.BOOL)


@_export
def isfinite(x, name=None):
    return _op("isfinite_v2", {"X": [x]}, out_dtype=VarType.BOOL)


@_export
def increment(x, value=1.0, name=None):
    return _L.increment(x, value)


# -- linalg (reference: paddle/tensor/linalg.py) ---------------------------
@_export
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _op("matmul_v2", {"X": [x], "Y": [y]},
               attrs={"trans_x": transpose_x, "trans_y": transpose_y})


mm = matmul
__all__.append("mm")


@_export
def dot(x, y, name=None):
    return _op("dot", {"X": [x], "Y": [y]})


@_export
def bmm(x, y, name=None):
    return _op("bmm", {"X": [x], "Y": [y]})


@_export
def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if p == "fro" and axis is None:
        return _op("frobenius_norm", {"X": [x]},
                   attrs={"dim": [], "keep_dim": keepdim,
                          "reduce_all": True})
    porder = 2.0 if p == "fro" else float(p)  # fro over an axis == 2-norm
    axis_ = axis if isinstance(axis, int) else -1
    return _op("p_norm", {"X": [x]},
               attrs={"porder": porder, "axis": axis_,
                      "keepdim": keepdim, "asvector": axis is None})


@_export
def t(x, name=None):
    if len(x.shape) <= 1:
        return x
    return _L.transpose(x, [1, 0])


@_export
def transpose(x, perm, name=None):
    return _L.transpose(x, perm)


@_export
def dist(x, y, p=2, name=None):
    return norm(subtract(x, y), p=float(p))


# -- manipulation (reference: paddle/tensor/manipulation.py) ---------------
for _name, _impl in (
    ("reshape", lambda x, shape, name=None: _L.reshape(x, shape)),
    ("concat", lambda x, axis=0, name=None: _L.concat(x, axis)),
    ("split", lambda x, num_or_sections, axis=0, name=None:
        _L.split(x, num_or_sections, dim=axis)),
    ("stack", lambda x, axis=0, name=None: _L.stack(x, axis)),
    ("unstack", lambda x, axis=0, num=None, name=None:
        _L.unstack(x, axis, num)),
    ("squeeze", lambda x, axis=None, name=None: _L.squeeze(
        x, [axis] if isinstance(axis, int) else (axis or []))),
    ("unsqueeze", lambda x, axis, name=None: _L.unsqueeze(
        x, [axis] if isinstance(axis, int) else list(axis))),
    ("flatten", lambda x, start_axis=0, stop_axis=-1, name=None:
        _op("flatten_contiguous_range", {"X": [x]},
            attrs={"start_axis": start_axis, "stop_axis": stop_axis})),
    ("gather", lambda x, index, axis=0, name=None:
        _op("gather", {"X": [x], "Index": [index]}, attrs={"axis": axis})),
    ("gather_nd", lambda x, index, name=None:
        _L.gather_nd(x, index)),
    ("scatter", lambda x, index, updates, overwrite=True, name=None:
        _op("scatter", {"X": [x], "Ids": [index], "Updates": [updates]},
            attrs={"overwrite": overwrite})),
    ("cast", lambda x, dtype: _L.cast(x, dtype)),
):
    _impl.__name__ = _name
    globals()[_name] = _impl
    __all__.append(_name)


@_export
def tile(x, repeat_times, name=None):
    return _op("tile", {"X": [x]},
               attrs={"repeat_times": list(repeat_times)})


@_export
def expand(x, shape, name=None):
    return _op("expand_v2", {"X": [x]}, attrs={"shape": list(shape)})


@_export
def expand_as(x, y, name=None):
    return _op("expand_as", {"X": [x], "Y": [y]})


@_export
def flip(x, axis, name=None):
    return _op("flip", {"X": [x]},
               attrs={"axis": [axis] if isinstance(axis, int)
                      else list(axis)})


@_export
def roll(x, shifts, axis=None, name=None):
    shifts = [shifts] if isinstance(shifts, int) else list(shifts)
    axis_ = ([axis] if isinstance(axis, int) else list(axis or []))
    return _op("roll", {"X": [x]}, attrs={"shifts": shifts, "axis": axis_})


@_export
def unique(x, return_index=False, return_inverse=False,
           return_counts=False, axis=None, dtype="int64", name=None):
    if return_index or return_inverse or return_counts:
        raise NotImplementedError(
            "unique(return_index/return_inverse/return_counts) is not "
            "supported yet; only the unique values are returned")
    return _op("unique", {"X": [x]}, attrs={"dtype": 3})


@_export
def broadcast_to(x, shape, name=None):
    return expand(x, shape)


# -- logic (reference: paddle/tensor/logic.py) -----------------------------
equal = _binary("equal", "equal")
not_equal = _binary("not_equal", "not_equal")
less_than = _binary("less_than", "less_than")
less_equal = _binary("less_equal", "less_equal")
greater_than = _binary("greater_than", "greater_than")
greater_equal = _binary("greater_equal", "greater_equal")
logical_and = _binary("logical_and", "logical_and")
logical_or = _binary("logical_or", "logical_or")
logical_xor = _binary("logical_xor", "logical_xor")
logical_not = _unary("logical_not", "logical_not")


@_export
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    diff = abs(subtract(x, y))
    tol = add(full([1], atol, "float32"),
              multiply(full([1], rtol, "float32"), abs(y)))
    ok = less_equal(diff, tol)
    if equal_nan:
        both_nan = logical_and(isnan(x), isnan(y))
        ok = logical_or(ok, both_nan)
    return all(ok)


@_export
def equal_all(x, y, name=None):
    return all(equal(x, y))


@_export
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition)
    return _op("where", {"Condition": [condition], "X": [x], "Y": [y]},
               x=x)


# -- search (reference: paddle/tensor/search.py) ---------------------------
@_export
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _op("arg_max", {"X": [x]},
               attrs={"axis": axis if axis is not None else -1,
                      "keepdims": keepdim, "flatten": axis is None},
               out_dtype=VarType.INT64)


@_export
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _op("arg_min", {"X": [x]},
               attrs={"axis": axis if axis is not None else -1,
                      "keepdims": keepdim, "flatten": axis is None},
               out_dtype=VarType.INT64)


@_export
def argsort(x, axis=-1, descending=False, name=None):
    return _L.argsort(x, axis, descending)[1]


@_export
def sort(x, axis=-1, descending=False, name=None):
    return _L.argsort(x, axis, descending)[0]


@_export
def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    helper = LayerHelper("top_k_v2")
    out = helper.create_variable_for_type_inference(x.dtype)
    indices = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("top_k_v2", inputs={"X": [x]},
                     outputs={"Out": [out], "Indices": [indices]},
                     attrs={"k": k, "axis": axis if axis is not None else -1,
                            "largest": largest, "sorted": sorted})
    return out, indices


@_export
def index_select(x, index, axis=0, name=None):
    return _op("index_select", {"X": [x], "Index": [index]},
               attrs={"dim": axis})


@_export
def index_sample(x, index, name=None):
    return _op("index_sample", {"X": [x], "Index": [index]})


@_export
def nonzero(x, as_tuple=False, name=None):
    return _op("where_index", {"Condition": [x]}, out_dtype=VarType.INT64)


@_export
def masked_select(x, mask, name=None):
    return _op("masked_select", {"X": [x], "Mask": [mask]})


# -- random (reference: paddle/tensor/random.py) ---------------------------
@_export
def rand(shape, dtype="float32", name=None):
    return _L.uniform_random(shape, dtype, 0.0, 1.0)


@_export
def randn(shape, dtype="float32", name=None):
    return _L.gaussian_random(shape, 0.0, 1.0, dtype=dtype)


@_export
def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    return _L.uniform_random(shape, dtype, min, max, seed)


@_export
def normal(mean=0.0, std=1.0, shape=None, name=None):
    return _L.gaussian_random(list(shape) if shape is not None else [1],
                              mean, std)


@_export
def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return _op("randint", {}, attrs={"low": low, "high": high,
                                     "shape": list(shape), "dtype": 3},
               out_dtype=VarType.INT64)


@_export
def randperm(n, dtype="int64", name=None):
    return _op("randperm", {}, attrs={"n": n, "dtype": 3},
               out_dtype=VarType.INT64)


# -- stat (reference: paddle/tensor/stat.py) -------------------------------
@_export
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return sqrt(var(x, axis, unbiased, keepdim))


@_export
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    m = mean(x, axis, True)
    sq = square(subtract(x, m))
    out = mean(sq, axis, keepdim)
    if unbiased:
        shape = list(x.shape)
        rank = len(shape)
        axes = (list(range(rank)) if axis is None
                else [axis] if isinstance(axis, int) else list(axis))
        axes = [a % rank for a in axes]
        dims = [int(shape[a]) for a in axes]
        if builtins.all(d >= 0 for d in dims):
            n = 1
            for d in dims:
                n *= d
            if n > 1:
                out = _L.scale(out, float(n) / (n - 1))
        else:
            # symbolic (-1) dim in the reduced axes: compute the n/(n-1)
            # correction from the runtime shape
            shp = _L.shape(x)
            picked = index_select(
                shp, to_tensor(np.asarray(axes, np.int64)), axis=0)
            n = cast(prod(picked), "float32")
            one = full([1], 1.0, "float32")
            corr = divide(n, maximum(subtract(n, one), one))
            out = multiply(out, corr)
    return out


@_export
def numel(x, name=None):
    return _op("size", {"Input": [x]}, out_dtype=VarType.INT64)


@_export
def median(x, axis=None, keepdim=False, name=None):
    sorted_x = sort(x, axis=axis if axis is not None else -1)
    # middle element along the axis (upper median for even n)
    ax = axis if axis is not None else -1
    n = int(x.shape[ax])
    lo = (n - 1) // 2
    hi = n // 2
    a = _L.slice(sorted_x, axes=[ax if ax >= 0 else len(x.shape) + ax],
                 starts=[lo], ends=[lo + 1])
    b = _L.slice(sorted_x, axes=[ax if ax >= 0 else len(x.shape) + ax],
                 starts=[hi], ends=[hi + 1])
    out = _L.scale(add(a, b), 0.5)
    if not keepdim:
        out = _L.squeeze(out, [ax])
    return out
