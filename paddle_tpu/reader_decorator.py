"""Sample-level reader decorators (reference: python/paddle/reader/decorator.py)."""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Thread


def shuffle(reader, buf_size):
    def impl():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return impl


def batch(reader, batch_size, drop_last=False):
    def impl():
        b = []
        for e in reader():
            b.append(e)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return impl


def compose(*readers):
    def impl():
        for outputs in zip(*[r() for r in readers]):
            yield sum([list(o) if isinstance(o, (list, tuple)) else [o]
                       for o in outputs], [])

    return impl


def chain(*readers):
    def impl():
        for r in readers:
            yield from r()

    return impl


def map_readers(func, *readers):
    def impl():
        for args in zip(*[r() for r in readers]):
            yield func(*args)

    return impl


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    def impl():
        in_q: Queue = Queue(buffer_size)
        out_q: Queue = Queue(buffer_size)
        end = object()

        def feed():
            for s in reader():
                in_q.put(s)
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                s = in_q.get()
                if s is end:
                    out_q.put(end)
                    return
                out_q.put(mapper(s))

        Thread(target=feed, daemon=True).start()
        workers = [Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
            else:
                yield item

    return impl


def buffered(reader, size):
    def impl():
        q: Queue = Queue(size)
        end = object()

        def feed():
            for s in reader():
                q.put(s)
            q.put(end)

        Thread(target=feed, daemon=True).start()
        while True:
            item = q.get()
            if item is end:
                return
            yield item

    return impl


def firstn(reader, n):
    def impl():
        return itertools.islice(reader(), n)

    return impl


def cache(reader):
    memory = []

    def impl():
        if memory:
            yield from memory
            return
        for e in reader():
            memory.append(e)
            yield e

    return impl
