"""Initializers append init ops into the startup program.

Reference: python/paddle/fluid/initializer.py (ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormalInitializer,
XavierInitializer, MSRAInitializer, NumpyArrayInitializer, BilinearInitializer).
"""
from __future__ import annotations

import math

import numpy as np

from .framework.core import Variable
from .framework.dtype import VarType


class Initializer:
    def __call__(self, var: Variable, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0, force_cpu: bool = False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "value": float(self.value),
                "dtype": int(var.dtype),
            },
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
                "dtype": int(var.dtype),
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
                "dtype": int(var.dtype),
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
                "dtype": int(var.dtype),
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fi)
        return NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        v = self.value
        if v.dtype in (np.float32, np.float64, np.float16):
            key = "fp32_values"
            vals = v.astype(np.float32).ravel().tolist()
        else:
            key = "int64_values" if v.dtype == np.int64 else "int32_values"
            vals = v.ravel().tolist()
        return block.append_op(
            "assign_value",
            outputs={"Out": [var.name]},
            attrs={"shape": list(v.shape), "dtype": int(var.dtype), key: vals},
        )


class BilinearInitializer(Initializer):
    """Bilinear upsample kernel init (reference: initializer.py Bilinear)."""

    def __call__(self, var, block):
        shape = var.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = shape[2] * shape[3]
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(i, shape)
            if idx[0] == idx[1]:
                weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return NumpyArrayInitializer(weight)(var, block)


# aliases matching the reference's public names
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer

_global_weight_initializer = None
_global_bias_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_initializer, _global_bias_initializer
    _global_weight_initializer = weight_init
    _global_bias_initializer = bias_init
