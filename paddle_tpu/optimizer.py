"""Optimizer classes appending update ops to the program.

Reference: python/paddle/fluid/optimizer.py (17 classes, :461 Optimizer
base, minimize flow = append_backward + _create_optimization_pass).
The update ops lower to jax in ops/optimizer_ops.py; update math runs
fused inside the same XLA program as forward/backward, which subsumes the
reference's fuse_all_optimizer_ops pass.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .backward import OP_ROLE_KEY, OP_ROLE_VAR_KEY, OpRole, append_backward
from .framework import unique_name
from .framework.core import (
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    in_dygraph_mode,
    program_guard,
)
from .framework.dtype import VarType
from .layer_helper import LayerHelper


def _dp_shard_spec():
    """Flat-state sharding target (FLAGS_dp_sharding, the Fleet
    `sharding` strategy analog): (dp_size, NamedSharding(P('dp'))) when
    the stage is >= 1 and a multi-device 'dp' mesh is registered, else
    None.  The dygraph fused-Adam buffers (master / moments) shard over
    the dp axis so each device holds 1/dp_size of the optimizer state —
    the ZeRO-1 rung of the ladder; stages 2/3 (gradient / parameter
    sharding) apply to the graph paths in parallel/data_parallel.py and
    framework/ir.py, not the eager fused update."""
    from .utils.flags import flag

    if not int(flag("dp_sharding") or 0):
        return None
    from .parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is None or "dp" not in mesh.axis_names:
        return None
    dp = int(mesh.shape["dp"])
    if dp <= 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec

    return dp, NamedSharding(mesh, PartitionSpec("dp"))


def _shard_flat(x, n, shard):
    """Pad a flat [n] buffer to a multiple of the dp axis and place it
    sharded.  Zero-pad is update-invariant for adam: zero grad on a zero
    moment leaves the pad rows zero forever.  Already-placed buffers
    (steady state) pass through without a device_put dispatch."""
    if shard is None:
        return x
    import jax
    import jax.numpy as jnp

    dp, sharding = shard
    pad = (-n) % dp
    if int(x.shape[0]) != n + pad:
        if int(x.shape[0]) > n:
            x = x[:n]  # drop a previous mesh size's zero pad
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    if getattr(x, "sharding", None) == sharding:
        return x
    return jax.device_put(x, sharding)


def _reshard_fused_state(state, n, shard, keys):
    """Normalize flat fused-optimizer buffers to the current
    FLAGS_dp_sharding mode: ON pads each buffer to a dp-axis multiple
    and shards it; OFF slices a previously padded buffer back to its
    logical length.  Values are carried either way, so flipping the
    flag mid-run continues the same trajectory (the mode-flip oracle)."""
    import jax
    import jax.numpy as jnp

    for k in keys:
        buf = state.get(k)
        if buf is None:
            continue
        if shard is not None:
            state[k] = _shard_flat(buf, n, shard)
        elif int(buf.shape[0]) > n:
            state[k] = buf[:n]
    if shard is not None:
        # scalar beta-pow accumulators ride along mesh-replicated so the
        # eager fused update sees one device set throughout
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(shard[1].mesh, PartitionSpec())
        for k in ("b1p", "b2p"):
            v = state.get(k)
            if v is not None and getattr(v, "sharding", None) != rep:
                state[k] = jax.device_put(v, rep)


class Optimizer:
    def __init__(self, learning_rate, parameter_list=None, regularization=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self.type = getattr(self, "type", "sgd")
        self._accumulators: Dict[str, Dict[str, Variable]] = defaultdict(dict)
        self._learning_rate_map: Dict[int, Variable] = {}
        self._global_step_var = None
        # dygraph support
        self._param_state: Dict[str, dict] = {}

    # ------------------------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        if program._uid in self._learning_rate_map:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program._uid] = self._learning_rate
            return
        from .layers import tensor as tensor_layers

        lr = tensor_layers.create_global_var(
            shape=[1], value=float(self._learning_rate), dtype="float32",
            persistable=True, name=unique_name.generate("learning_rate"),
        )
        self._learning_rate_map[program._uid] = lr

    def _global_learning_rate(self):
        return self._learning_rate_map.get(default_main_program()._uid)

    def _create_param_lr(self, param):
        lr = self._global_learning_rate()
        plr = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        if plr == 1.0:
            return lr
        helper = LayerHelper("param_lr")
        out = helper.create_variable_for_type_inference(lr.dtype, stop_gradient=True)
        helper.append_op("scale", inputs={"X": [lr]}, outputs={"Out": [out]},
                        attrs={"scale": float(plr), OP_ROLE_KEY: OpRole.Optimize})
        return out

    # ------------------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        var_name = unique_name.generate(f"{param.name}_{name}")
        main_block = default_main_program().global_block()
        var = main_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        startup_block = default_startup_program().global_block()
        startup_block.create_var(name=var_name, shape=shape, dtype=dtype,
                                 persistable=True)
        startup_block.append_op(
            "fill_constant", outputs={"Out": [var_name]},
            attrs={"shape": shape, "value": float(fill_value), "dtype": int(dtype)},
        )
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # per-optimizer hooks ----------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    # ------------------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        parameter_list = parameter_list or self._parameter_list
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        if self._grad_clip is not None:
            params_grads = self._grad_clip._process(params_grads)
        else:
            from .clip import _global_clip

            if _global_clip[0] is not None:
                params_grads = _global_clip[0]._process(params_grads)
        params_grads = self._append_regularization_ops(params_grads)
        return self._create_optimization_pass(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        with program_guard(default_main_program(), startup_program):
            return self.apply_gradients(params_grads)

    def _append_regularization_ops(self, params_grads):
        out = []
        block = default_main_program().global_block()
        for p, g in params_grads:
            reg = getattr(p, "regularizer", None) or self.regularization
            if g is None or reg is None:
                out.append((p, g))
            else:
                out.append((p, reg(p, g, block)))
        return out

    def _create_optimization_pass(self, params_grads):
        main_block = default_main_program().global_block()
        self._create_global_learning_rate()
        self._create_accumulators(main_block, [p for p, g in params_grads if g is not None])
        optimize_ops = []
        for p, g in params_grads:
            if g is None:
                continue
            op = self._append_optimize_op(main_block, (p, g))
            if op is not None:
                op.attrs[OP_ROLE_KEY] = OpRole.Optimize
                op.attrs[OP_ROLE_VAR_KEY] = [p.name, g.name]
                optimize_ops.append(op)
        self._finish_update(main_block, params_grads)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        if in_dygraph_mode():
            from .dygraph.base import _dygraph_minimize

            return _dygraph_minimize(self, loss, parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def clear_gradients(self):
        """dygraph API — grads are recomputed per step, nothing to clear."""
        from .dygraph import base as dy_base

        dy_base._clear_grads(self._parameter_list)

    # -- dygraph eager updates ------------------------------------------
    # (reference: dygraph mode runs the same optimizer kernels eagerly via
    # the imperative tracer; here via registry.eager_call)
    def _eager_lr(self):
        import jax.numpy as jnp

        lr = self._learning_rate
        if callable(lr):
            lr = lr()
        return jnp.asarray([float(lr)], jnp.float32)

    def _eager_regularize(self, p, g):
        from .regularizer import L1DecayRegularizer, L2DecayRegularizer
        import jax.numpy as jnp

        reg = getattr(p, "regularizer", None) or self.regularization
        if isinstance(reg, L2DecayRegularizer):
            return g + reg.regularization_coeff * p._value
        if isinstance(reg, L1DecayRegularizer):
            return g + reg.regularization_coeff * jnp.sign(p._value)
        return g

    def _dygraph_apply(self, params_grads):
        lr = self._eager_lr()
        for p, g in params_grads:
            if g is None:
                continue
            g = self._eager_regularize(p, g)
            state = self._param_state.setdefault(p.name, {})
            self._eager_update(p, g, state, lr)

    def _eager_update(self, p, g, state, lr):
        raise NotImplementedError(
            f"{type(self).__name__} has no dygraph update path yet"
        )

    @property
    def current_step_lr(self):
        lr = self._learning_rate
        return lr() if callable(lr) else lr

    def set_lr(self, value):
        self._learning_rate = float(value)

    def state_dict(self):
        out = {}
        for name, accs in self._accumulators.items():
            for pname, var in accs.items():
                out[var.name] = var
        return out


class SGDOptimizer(Optimizer):
    """reference: optimizer.py SGDOptimizer."""

    type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p]},
        )

    def _eager_update(self, p, g, state, lr):
        from .ops.registry import eager_call

        outs = eager_call("sgd",
                          {"Param": [p._value], "Grad": [g], "LearningRate": [lr]},
                          {}, {"ParamOut": 1})
        p._value = outs["ParamOut"][0]


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )

    def _eager_update(self, p, g, state, lr):
        import jax.numpy as jnp

        from .ops.registry import eager_call

        if "velocity" not in state:
            state["velocity"] = jnp.zeros_like(p._value)
        outs = eager_call(
            "momentum",
            {"Param": [p._value], "Grad": [g], "Velocity": [state["velocity"]],
             "LearningRate": [lr]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
            {"ParamOut": 1, "VelocityOut": 1},
        )
        p._value = outs["ParamOut"][0]
        state["velocity"] = outs["VelocityOut"][0]


class DGCMomentumOptimizer(MomentumOptimizer):
    """Deep Gradient Compression momentum (reference: optimizer.py:1071
    DGCMomentumOptimizer + dgc_op.cc + sparse_all_reduce_op_handle.cc).

    For parameters with >= ``dgc_size_threshold`` elements, gradients are
    exchanged sparsely through the fused ``dgc`` op (top-k + momentum
    correction + residual accumulation — ops/dgc_ops.py); smaller
    parameters use dense allreduce + classic momentum, and every
    parameter uses dense exchange before ``rampup_begin_step``
    (dgc_momentum op switches momentum→sgd at the same boundary).
    Self-contained for data-parallel programs: inserts its own
    ``c_allreduce_sum`` for the dense path, so no GradAllReduce
    transpile should be applied on top (fleet collective skips it when
    ``use_dgc`` is set).
    """

    DGC_SIZE_THRESHOLD = 16384  # reference: same cutoff

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 num_trainers=None, **kwargs):
        super().__init__(learning_rate, momentum,
                         use_nesterov=use_nesterov, **kwargs)
        self.type = "dgc_momentum"
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = int(rampup_step)
        self._sparsity = [float(s) for s in sparsity]
        self._num_trainers = num_trainers
        self._step_var = None

    def _is_dgc_param(self, param) -> bool:
        import numpy as _np

        return int(_np.prod([abs(s) for s in param.shape])) >= \
            self.DGC_SIZE_THRESHOLD

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)
            if self._is_dgc_param(p):
                self._add_accumulator("dgc_u", p)
                self._add_accumulator("dgc_v", p)

    def _get_step_var(self, block):
        if self._step_var is None:
            from .framework import unique_name as _un

            name = _un.generate("dgc_global_step")
            self._step_var = block.create_var(
                name=name, shape=[1], dtype=VarType.INT32, persistable=True,
                stop_gradient=True)
            startup = default_startup_program().global_block()
            startup.create_var(name=name, shape=[1], dtype=VarType.INT32,
                               persistable=True)
            startup.append_op(
                "fill_constant", outputs={"Out": [name]},
                attrs={"shape": [1], "value": 0.0,
                       "dtype": int(VarType.INT32)})
        return self._step_var

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        vel = self._get_accumulator("velocity", p)
        lr = self._create_param_lr(p)
        step = self._get_step_var(block)

        if not self._is_dgc_param(p):
            # dense path: allreduce-mean + momentum
            block.append_op(
                "c_allreduce_sum", inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"ring_id": 0, "use_mean": True})
            return block.append_op(
                "momentum",
                inputs={"Param": [p], "Grad": [g], "Velocity": [vel],
                        "LearningRate": [lr]},
                outputs={"ParamOut": [p], "VelocityOut": [vel]},
                attrs={"mu": self._momentum,
                       "use_nesterov": self._use_nesterov})

        u = self._get_accumulator("dgc_u", p)
        v = self._get_accumulator("dgc_v", p)
        encoded = block.create_var(
            name=unique_name.generate(f"{p.name}_dgc_encoded"),
            dtype=p.dtype, stop_gradient=True)
        gathered = block.create_var(
            name=unique_name.generate(f"{p.name}_dgc_idx"),
            dtype=VarType.INT32, stop_gradient=True)
        agg = block.create_var(
            name=unique_name.generate(f"{p.name}_dgc_agg"),
            dtype=p.dtype, stop_gradient=True)
        block.append_op(
            "dgc",
            inputs={"U": [u], "V": [v], "Grad": [g],
                    "current_step": [step]},
            outputs={"U_out": [u], "V_out": [v], "Grad_out": [agg],
                     "EncodeGrad": [encoded], "GatherBuff": [gathered]},
            attrs={"m": self._momentum, "use_nesterov": self._use_nesterov,
                   "sparsity": self._sparsity,
                   "rampup_begin_step": self._rampup_begin_step,
                   "rampup_step": self._rampup_step, "ring_id": 0})
        return block.append_op(
            "dgc_momentum",
            inputs={"Param": [p], "Grad": [agg], "Velocity": [vel],
                    "LearningRate": [lr], "current_step": [step]},
            outputs={"ParamOut": [p], "VelocityOut": [vel]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov,
                   "rampup_begin_step": self._rampup_begin_step})

    def _finish_update(self, block, params_grads):
        if self._step_var is not None:
            block.append_op(
                "increment", inputs={"X": [self._step_var]},
                outputs={"Out": [self._step_var]}, attrs={"step": 1.0})


class LarsMomentumOptimizer(Optimizer):
    type = "lars_momentum"

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
        )


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=1.0, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=1.0, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            "adam",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "lazy_mode": self._lazy_mode},
        )

    # -- multi-tensor fused path (dygraph) ------------------------------
    # reference: ir/fuse_optimizer_ops_pass/fuse_adam_op_pass.cc fuses the
    # per-parameter adam ops of a static graph into one op over coalesced
    # buffers.  Here the same rewrite happens at trace time: all dense
    # f32 params flatten into one buffer and ONE adam kernel updates
    # them, collapsing ~4 tiny HLO kernels per parameter into a handful
    # of large bandwidth-bound ones.  LAMB must NOT take this path (its
    # trust ratio is a per-parameter norm), so it is gated on self.type.

    def _dygraph_apply(self, params_grads):
        import jax
        import jax.numpy as jnp

        from .utils import flags

        if (self.type not in ("adam", "adamw")
                or not flags._flags.get("FLAGS_fuse_optimizer_dygraph", True)):
            return super()._dygraph_apply(params_grads)
        lr = self._eager_lr()
        fused, fused_mp, single = [], [], []
        for p, g in params_grads:
            if g is None:
                continue
            g = self._eager_regularize(p, g)
            if (isinstance(g, jax.Array) and g.dtype == jnp.float32
                    and p._value.dtype == jnp.float32):
                fused.append((p, g))
            elif (isinstance(g, jax.Array)
                  and p._value.dtype in (jnp.bfloat16, jnp.float16)
                  and jnp.issubdtype(g.dtype, jnp.floating)):
                # low-precision-resident param (amp O2): fused update runs
                # on the f32 master copy kept inside the optimizer state
                fused_mp.append((p, g))
            else:
                single.append((p, g))
        for p, g in single:
            state = self._param_state.setdefault(p.name, {})
            self._eager_update(p, g, state, lr)
        if fused_mp:
            fused_mp, deferred_mp = self._fused_pow_groups(
                fused_mp, "@fused_mp", "_fused_mp_layout")
            for p, g in deferred_mp:
                state = self._param_state.setdefault(p.name, {})
                self._eager_update(p, g, state, lr)
            if fused_mp:
                self._apply_fused_mp(fused_mp, lr)
        if fused:
            # advisor r4: a param whose carried (b1p, b2p) schedule
            # disagrees with the fused buffer's cannot share its scalar
            # bias correction — keep it on the per-param path
            fused, deferred = self._fused_pow_groups(
                fused, "@fused", "_fused_layout")
            for p, g in deferred:
                state = self._param_state.setdefault(p.name, {})
                self._eager_update(p, g, state, lr)
        if not fused:
            return
        layout = tuple((p.name, int(np.prod(p._value.shape) if p._value.shape
                                    else 1)) for p, _ in fused)
        state = self._param_state.setdefault("@fused", {})
        if getattr(self, "_fused_layout", None) != layout or "m1" not in state:
            self._migrate_fused_state(state, layout, fused)
        total = sum(n for _, n in layout)
        shard = _dp_shard_spec()
        _reshard_fused_state(state, total, shard, ("m1", "m2"))
        flat_p = _shard_flat(
            jnp.concatenate([jnp.ravel(p._value) for p, _ in fused]),
            total, shard)
        flat_g = _shard_flat(
            jnp.concatenate([jnp.ravel(g) for _, g in fused]), total, shard)
        outs = self._fused_adam_call(flat_p, flat_g, state, lr)
        new_flat = outs["ParamOut"][0]
        state["m1"] = outs["Moment1Out"][0]
        state["m2"] = outs["Moment2Out"][0]
        state["b1p"] = outs["Beta1PowOut"][0]
        state["b2p"] = outs["Beta2PowOut"][0]
        off = 0
        for p, _ in fused:
            n = int(np.prod(p._value.shape) if p._value.shape else 1)
            p._value = jnp.reshape(new_flat[off:off + n], p._value.shape)
            off += n

    def _fused_adam_call(self, flat_p, flat_g, state, lr):
        from .ops.registry import eager_call

        return eager_call(
            self.type,
            {"Param": [flat_p], "Grad": [flat_g], "Moment1": [state["m1"]],
             "Moment2": [state["m2"]], "Beta1Pow": [state["b1p"]],
             "Beta2Pow": [state["b2p"]], "LearningRate": [lr]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon,
             **({"coeff": getattr(self, "_coeff", 0.0), "with_decay": True}
                if self.type == "adamw" else {})},
            {"ParamOut": 1, "Moment1Out": 1, "Moment2Out": 1,
             "Beta1PowOut": 1, "Beta2PowOut": 1},
        )

    # -- master-weight fused path (amp O2, bf16/fp16-resident params) ----
    # reference: contrib/mixed_precision/decorator.py cast_model_to_fp16 +
    # the multi_precision attr of adam_op.cc — params live in low
    # precision (so the forward reads them with ZERO boundary casts) and
    # the f32 master copy exists only here, inside the fused optimizer
    # state.  One flat adam kernel updates the master; the low-precision
    # shards the model sees are sliced+cast straight out of it.

    def _apply_fused_mp(self, fused, lr):
        import jax.numpy as jnp

        layout = tuple((p.name,
                        int(np.prod(p._value.shape) if p._value.shape else 1),
                        str(p._value.dtype)) for p, _ in fused)
        state = self._param_state.setdefault("@fused_mp", {})
        if getattr(self, "_fused_mp_layout", None) != layout \
                or "master" not in state:
            self._migrate_fused_mp_state(state, layout, fused)
        total = sum(n for _, n, _ in layout)
        shard = _dp_shard_spec()
        _reshard_fused_state(state, total, shard, ("master", "m1", "m2"))
        flat_g = _shard_flat(
            jnp.concatenate(
                [jnp.ravel(g).astype(jnp.float32) for _, g in fused]),
            total, shard)
        outs = self._fused_adam_call(state["master"], flat_g, state, lr)
        state["master"] = outs["ParamOut"][0]
        state["m1"] = outs["Moment1Out"][0]
        state["m2"] = outs["Moment2Out"][0]
        state["b1p"] = outs["Beta1PowOut"][0]
        state["b2p"] = outs["Beta2PowOut"][0]
        new_master = state["master"]
        off = 0
        for p, _ in fused:
            n = int(np.prod(p._value.shape) if p._value.shape else 1)
            p._value = jnp.reshape(
                new_master[off:off + n], p._value.shape).astype(p._value.dtype)
            off += n

    def _migrate_fused_mp_state(self, state, layout, fused):
        """(Re)build the flat master/moment buffers for a new
        low-precision parameter layout.  New params seed their master
        from the current param value (carrying any per-param moments
        they trained with — the pow gate upstream guarantees their
        schedule matches); params already in the old layout carry
        master AND moments byte-exact; params LEAVING the buffer stash
        their moments+pows per-param so a later _eager_update resumes
        instead of restarting bias correction (same contract as the f32
        _migrate_fused_state)."""
        import jax.numpy as jnp

        old_layout = getattr(self, "_fused_mp_layout", None)
        per_param = {}
        if old_layout and "master" in state:
            off = 0
            for name, n, _ in old_layout:
                per_param[name] = (state["master"][off:off + n],
                                   state["m1"][off:off + n],
                                   state["m2"][off:off + n])
                off += n
            new_names = {name for name, _, _ in layout}
            for name, _, _ in old_layout:
                if name not in new_names:
                    self._param_state[name] = {
                        "master": per_param[name][0],
                        "m1": per_param[name][1], "m2": per_param[name][2],
                        "b1p": state["b1p"], "b2p": state["b2p"]}
        masters, m1s, m2s = [], [], []
        carried_pows = None
        for p, _ in fused:
            n = int(np.prod(p._value.shape) if p._value.shape else 1)
            if p.name in per_param:
                ms, m1, m2 = per_param[p.name]
            else:
                pst = self._param_state.get(p.name, {})
                # prefer the per-param f32 master (kept by _eager_update
                # for low-precision params) over re-upcasting bf16
                ms = (jnp.ravel(pst["master"]) if "master" in pst
                      else jnp.ravel(p._value).astype(jnp.float32))
                if "m1" in pst:
                    m1 = jnp.ravel(pst["m1"]).astype(jnp.float32)
                    m2 = jnp.ravel(pst["m2"]).astype(jnp.float32)
                    if "b1p" in pst:
                        carried_pows = (pst["b1p"], pst["b2p"])
                    self._param_state.pop(p.name, None)
                else:
                    m1 = jnp.zeros((n,), jnp.float32)
                    m2 = jnp.zeros((n,), jnp.float32)
            masters.append(ms)
            m1s.append(m1)
            m2s.append(m2)
        state["master"] = jnp.concatenate(masters)
        state["m1"] = jnp.concatenate(m1s)
        state["m2"] = jnp.concatenate(m2s)
        # per-param -> fresh-buffer migration keeps the beta-pow
        # schedule (the pow gate guarantees all carried sources agree);
        # resetting to 1 would restart bias correction mid-run
        if carried_pows is not None and "b1p" not in state:
            state["b1p"], state["b2p"] = carried_pows
        state.setdefault("b1p", jnp.ones((1,), jnp.float32))
        state.setdefault("b2p", jnp.ones((1,), jnp.float32))
        self._fused_mp_layout = layout

    def _fused_pow_groups(self, fused, state_key, layout_attr):
        """Split fused candidates into (fusable, per_param) by beta-pow
        schedule.  The fused buffer keeps ONE (b1p, b2p) pair; a param
        whose carried per-param pows differ — or a brand-new param
        joining a mid-schedule buffer — would inherit a wrong bias
        correction, so it stays per-param.  Params already CARRIED BY
        the buffer (present in the current layout) share its schedule
        by construction and always fuse.  Traced (in-jit) states skip
        the value check — the state structure is fixed per compiled
        step, and fresh optimizers (the jit_train_step path) are
        homogeneous anyway."""
        import jax

        def conc(x):
            if isinstance(x, jax.core.Tracer):
                return None
            return float(np.asarray(x).ravel()[0])

        in_buffer = {name for name, *_ in (getattr(self, layout_attr, None)
                                           or ())}
        st = self._param_state.get(state_key, {})
        target = None
        if "b1p" in st:
            t1, t2 = conc(st["b1p"]), conc(st["b2p"])
            if t1 is None:
                return fused, []
            target = (t1, t2)
        fusable, groups, new_params = [], {}, []
        for pg in fused:
            name = pg[0].name
            pst = self._param_state.get(name, {})
            if name in in_buffer and "m1" not in pst:
                fusable.append(pg)  # lives in the flat buffer already
            elif "m1" in pst and "b1p" in pst:
                c1, c2 = conc(pst["b1p"]), conc(pst["b2p"])
                if c1 is None:
                    return fused, []
                groups.setdefault((c1, c2), []).append(pg)
            else:
                new_params.append(pg)
        if target is None:
            if not groups:
                return fused, []
            target = max(groups, key=lambda k: len(groups[k]))
        defer = []
        for pows, pgs in groups.items():
            ok = all(abs(a - b) <= 1e-6 * max(1.0, abs(b))
                     for a, b in zip(pows, target))
            (fusable if ok else defer).extend(pgs)
        # new params start at unity pows: they may only join a buffer
        # whose schedule is still at step 0
        if all(abs(v - 1.0) <= 1e-9 for v in target):
            fusable.extend(new_params)
        else:
            defer.extend(new_params)
        order = {id(pg): i for i, pg in enumerate(fused)}
        fusable.sort(key=lambda pg: order[id(pg)])
        return fusable, defer

    def _migrate_fused_state(self, state, layout, fused):
        """(Re)build the flat moment buffers for a new parameter layout,
        carrying over any existing per-parameter or flat state.  The
        _fused_pow_groups gate upstream guarantees every carried source
        here shares one (b1p, b2p) schedule."""
        import jax.numpy as jnp

        old_layout = getattr(self, "_fused_layout", None)
        per_param = {}
        if old_layout and "m1" in state:
            off = 0
            for name, n in old_layout:
                per_param[name] = (state["m1"][off:off + n],
                                   state["m2"][off:off + n])
                off += n
            # params leaving the fused set keep their moments in the
            # per-param store (with the beta pows) so a later re-entry
            # resumes instead of restarting bias correction at zero
            new_names = {name for name, _ in layout}
            for name, _ in old_layout:
                if name not in new_names:
                    self._param_state[name] = {
                        "m1": per_param[name][0], "m2": per_param[name][1],
                        "b1p": state["b1p"], "b2p": state["b2p"]}
        m1s, m2s = [], []
        carried_pows = None
        for p, _ in fused:
            n = int(np.prod(p._value.shape) if p._value.shape else 1)
            if p.name in per_param:
                m1s.append(per_param[p.name][0])
                m2s.append(per_param[p.name][1])
            elif p.name in self._param_state and \
                    "m1" in self._param_state[p.name]:
                st = self._param_state[p.name]
                m1s.append(jnp.ravel(st["m1"]))
                m2s.append(jnp.ravel(st["m2"]))
                carried_pows = (st["b1p"], st["b2p"])
                # the buffer owns this param's state now: a stale
                # per-param entry would make the pow gate evict it on
                # the NEXT step (code-review r5)
                self._param_state.pop(p.name, None)
            else:
                m1s.append(jnp.zeros((n,), jnp.float32))
                m2s.append(jnp.zeros((n,), jnp.float32))
        state["m1"] = jnp.concatenate(m1s)
        state["m2"] = jnp.concatenate(m2s)
        # migrating mid-run (per-param -> fused) must keep the beta-power
        # accumulators: resetting them to 1 would restart bias correction
        # and spike the effective LR by 1/(1-beta1) on the next step
        if carried_pows is not None and "b1p" not in state:
            state["b1p"], state["b2p"] = carried_pows
        state.setdefault("b1p", jnp.ones((1,), jnp.float32))
        state.setdefault("b2p", jnp.ones((1,), jnp.float32))
        self._fused_layout = layout

    def _eager_update(self, p, g, state, lr):
        import jax.numpy as jnp

        from .ops.registry import eager_call

        # low-precision-resident params keep the O2 master-weight
        # contract even on the per-param path (code-review r5): a f32
        # master lives in the state, moments stay f32, and the bf16
        # param is the cast of the master after every step
        low_prec = p._value.dtype in (jnp.bfloat16, jnp.float16)
        if low_prec and "master" in state:
            # may arrive flat from a fused-buffer migration stash
            pv = jnp.reshape(state["master"], jnp.shape(p._value))
        elif low_prec:
            pv = p._value.astype(jnp.float32)
        else:
            pv = p._value
        if "m1" not in state:
            state["m1"] = jnp.zeros_like(pv)
            state["m2"] = jnp.zeros_like(pv)
            state["b1p"] = jnp.ones((1,), jnp.float32)
            state["b2p"] = jnp.ones((1,), jnp.float32)
        elif jnp.shape(state["m1"]) != jnp.shape(pv):
            # moments stashed flat by a fused-set migration
            state["m1"] = jnp.reshape(state["m1"], jnp.shape(pv))
            state["m2"] = jnp.reshape(state["m2"], jnp.shape(pv))
        if low_prec:
            g = jnp.asarray(g).astype(jnp.float32)
        outs = eager_call(
            self.type,
            {"Param": [pv], "Grad": [g], "Moment1": [state["m1"]],
             "Moment2": [state["m2"]], "Beta1Pow": [state["b1p"]],
             "Beta2Pow": [state["b2p"]], "LearningRate": [lr]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon,
             **({"coeff": getattr(self, "_coeff", 0.0), "with_decay": True}
                if self.type == "adamw" else {}),
             **({"weight_decay": getattr(self, "_weight_decay", 0.0)}
                if self.type == "lamb" else {})},
            {"ParamOut": 1, "Moment1Out": 1, "Moment2Out": 1,
             "Beta1PowOut": 1, "Beta2PowOut": 1},
        )
        if low_prec:
            state["master"] = outs["ParamOut"][0]
            p._value = state["master"].astype(p._value.dtype)
        else:
            p._value = outs["ParamOut"][0]
        state["m1"] = outs["Moment1Out"][0]
        state["m2"] = outs["Moment2Out"][0]
        state["b1p"] = outs["Beta1PowOut"][0]
        state["b2p"] = outs["Beta2PowOut"][0]


class AdamWOptimizer(AdamOptimizer):
    type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._coeff = weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            "adamw",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "coeff": self._coeff,
                   "with_decay": True},
        )


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon},
        )

    def _eager_update(self, p, g, state, lr):
        import jax.numpy as jnp

        from .ops.registry import eager_call

        if "moment" not in state:
            state["moment"] = jnp.full_like(p._value, self._initial)
        outs = eager_call(
            "adagrad",
            {"Param": [p._value], "Grad": [g], "Moment": [state["moment"]],
             "LearningRate": [lr]},
            {"epsilon": self._epsilon},
            {"ParamOut": 1, "MomentOut": 1},
        )
        p._value = outs["ParamOut"][0]
        state["moment"] = outs["MomentOut"][0]


class DecayedAdagradOptimizer(Optimizer):
    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    type = "adadelta"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("avg_squared_grad", p)
        asu = self._get_accumulator("avg_squared_update", p)
        return block.append_op(
            "adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg],
                    "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class AdamaxOptimizer(Optimizer):
    type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        inf = self._get_accumulator("inf_norm", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        op = block.append_op(
            "adamax",
            inputs={"Param": [p], "Grad": [g], "Moment": [m], "InfNorm": [inf],
                    "Beta1Pow": [b1p],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [m], "InfNormOut": [inf]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )
        # beta1_pow update (reference does this in _finish_update)
        block.append_op("scale", inputs={"X": [b1p]}, outputs={"Out": [b1p]},
                        attrs={"scale": self._beta1, OP_ROLE_KEY: OpRole.Optimize})
        return op


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator("momentum", p)
        ms = self._get_accumulator("mean_square", p)
        mg = self._get_accumulator("mean_grad", p)
        return block.append_op(
            "rmsprop",
            inputs={"Param": [p], "Grad": [g], "Moment": [mom],
                    "MeanSquare": [ms], "MeanGrad": [mg],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [mom],
                     "MeanSquareOut": [ms], "MeanGradOut": [mg]},
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            inputs={"Param": [p], "Grad": [g], "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class LambOptimizer(AdamOptimizer):
    type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, exclude_from_weight_decay_fn=None,
                 **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        return block.append_op(
            "lamb",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": wd},
        )


class DpsgdOptimizer(Optimizer):
    type = "dpsgd"

    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "dpsgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p]},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma},
        )


class RecomputeOptimizer(Optimizer):
    """Activation recompute (reference: optimizer.py:3858).

    TPU-native: instead of rewriting the backward program to re-emit
    forward ops between checkpoints, grad-op vjp replay already recomputes
    the forward inside the grad ops; marking checkpoints wraps segments in
    jax.checkpoint at executor trace time (planned hook).  Until that
    hook lands, the vjp-replay + XLA rematerialization default already
    provides recompute-like memory behavior.
    """

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program, parameter_list,
                                        no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._optimizer.minimize(loss, startup_program, parameter_list,
                                        no_grad_set)

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


class PipelineOptimizer:
    """Pipeline-parallel wrapper (reference: optimizer.py:3556-3640 —
    splits the program by cut-vars into sections across heterogeneous
    places, run by PipelineTrainer/SectionWorker threads+queues).

    TPU-native: ``minimize`` runs the inner optimizer as usual, then
    attaches ``program._pipeline_opt`` metadata (loss, microbatch count,
    cut vars, param/grad pairs).  ``Executor.run`` detects the metadata
    and executes via ``parallel.pipeline.run_pipeline``: forward sections
    traced into one jit, ``lax.scan`` over microbatches accumulating
    grads, program's own optimizer ops applying the update.  Homogeneous
    stages can instead use ``parallel.pipeline.spmd_pipeline`` (ppermute
    over a `pp` mesh axis).
    """

    def __init__(self, optimizer, num_microbatches=1, cut_list=None,
                 place_list=None, concurrency_list=None, queue_size=30,
                 sync_steps=1, start_cpu_core_id=0):
        self._optimizer = optimizer
        self._num_microbatches = int(num_microbatches)
        self._cut_list = cut_list
        # place/concurrency/queue knobs are accepted for API parity; the
        # TPU schedule has no host threads or queues to configure.
        self._place_list = place_list
        self._concurrency_list = concurrency_list
        self._queue_size = queue_size
        self._sync_steps = sync_steps

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        opt_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        program = loss.block.program
        cut_names = []
        for group in (self._cut_list or []):
            vars_ = group if isinstance(group, (list, tuple)) else [group]
            for v in vars_:
                cut_names.append(v if isinstance(v, str) else v.name)
        program._pipeline_opt = {
            "loss_name": loss.name,
            "num_microbatches": self._num_microbatches,
            "cut_vars": cut_names,
            "params_grads": [(p.name, g.name) for p, g in params_grads],
        }
        return opt_ops, params_grads

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


class LookaheadOptimizer:
    """reference: optimizer.py:4150 — slow/fast weight interpolation."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        mini_out = self.inner_optimizer.minimize(loss, startup_program)
        # slow-weight update every k steps is approximated by EMA toward
        # fast weights each step with rate alpha/k (program-rewrite-free).
        helper = LayerHelper("lookahead")
        block = default_main_program().global_block()
        rate = self.alpha / float(self.k)
        for p in default_main_program().all_parameters():
            slow = self._slow_var(p)
            mixed = helper.create_variable_for_type_inference(p.dtype)
            block.append_op("scale", inputs={"X": [slow]}, outputs={"Out": [slow]},
                            attrs={"scale": 1.0 - rate, OP_ROLE_KEY: OpRole.Optimize})
            block.append_op("scale", inputs={"X": [p]}, outputs={"Out": [mixed]},
                            attrs={"scale": rate, OP_ROLE_KEY: OpRole.Optimize})
            block.append_op("sum", inputs={"X": [slow, mixed]},
                            outputs={"Out": [slow]},
                            attrs={OP_ROLE_KEY: OpRole.Optimize})
        return mini_out

    def _slow_var(self, p):
        name = p.name + "@SLOW"
        block = default_main_program().global_block()
        if block.has_var(name):
            return block.var(name)
        var = block.create_var(name=name, shape=p.shape, dtype=p.dtype,
                               persistable=True, stop_gradient=True)
        sblock = default_startup_program().global_block()
        sblock.create_var(name=name, shape=p.shape, dtype=p.dtype, persistable=True)
        sblock.append_op("assign", inputs={"X": [p.name]}, outputs={"Out": [name]})
        return var


class ExponentialMovingAverage:
    """reference: optimizer.py ExponentialMovingAverage."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._ema_vars = {}

    def update(self):
        block = default_main_program().global_block()
        helper = LayerHelper("ema")
        for p in default_main_program().all_parameters():
            ema = self._create_ema_var(p)
            tmp = helper.create_variable_for_type_inference(p.dtype)
            block.append_op("scale", inputs={"X": [ema]}, outputs={"Out": [ema]},
                            attrs={"scale": self._decay})
            block.append_op("scale", inputs={"X": [p]}, outputs={"Out": [tmp]},
                            attrs={"scale": 1.0 - self._decay})
            block.append_op("sum", inputs={"X": [ema, tmp]}, outputs={"Out": [ema]})

    def _create_ema_var(self, p):
        name = p.name + "@EMA" + self._name
        if name in self._ema_vars:
            return self._ema_vars[name]
        block = default_main_program().global_block()
        var = block.create_var(name=name, shape=p.shape, dtype=p.dtype,
                               persistable=True, stop_gradient=True)
        sblock = default_startup_program().global_block()
        sblock.create_var(name=name, shape=p.shape, dtype=p.dtype, persistable=True)
        sblock.append_op("assign", inputs={"X": [p.name]}, outputs={"Out": [name]})
        self._ema_vars[name] = var
        return var

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            from .framework.scope import global_scope
            import numpy as np

            saved = {}
            scope = global_scope()
            for p in default_main_program().all_parameters():
                ema_name = p.name + "@EMA" + self._name
                if scope.has(ema_name):
                    saved[p.name] = scope.get(p.name)
                    scope.set(p.name, scope.get(ema_name))
            try:
                yield
            finally:
                if need_restore:
                    for name, val in saved.items():
                        scope.set(name, val)

        return _guard()

    def restore(self, executor=None):
        pass


class ModelAverage(Optimizer):
    """Windowed parameter averaging (reference: optimizer.py ModelAverage
    + average_accumulates_op.h).  Appends an average_accumulates op per
    trainable parameter to the CURRENT main program (call after
    optimizer.minimize, like the reference); ``apply()`` swaps params for
    (sum_1+sum_2+sum_3)/(num_accumulates+old_num_accumulates) and
    ``restore()``/context-exit swaps back."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization=regularization, name=name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._accums = []  # (param_name, s1, s2, s3, na, ona, nu)
        main = default_main_program()
        startup = default_startup_program()
        for p in main.all_parameters():
            if not getattr(p, "trainable", True):
                continue
            self._append_average_accumulate_op(main, startup, p)
        self._restore_vals = None

    def _append_average_accumulate_op(self, main, startup, param):
        block = main.global_block()
        sblock = startup.global_block()

        def acc(suffix, shape, dtype, value=0.0):
            name = f"{param.name}_{suffix}{self._name or ''}"
            block.create_var(name=name, shape=shape, dtype=dtype,
                             persistable=True)
            sblock.create_var(name=name, shape=shape, dtype=dtype,
                              persistable=True)
            sblock.append_op(
                "fill_constant", inputs={},
                outputs={"Out": [name]},
                attrs={"shape": list(shape), "value": value,
                       "dtype": int(VarType(dtype))})
            return name

        shape = [s for s in param.shape]
        s1 = acc("sum_1", shape, param.dtype)
        s2 = acc("sum_2", shape, param.dtype)
        s3 = acc("sum_3", shape, param.dtype)
        na = acc("num_accumulates", [1], VarType.INT64)
        ona = acc("old_num_accumulates", [1], VarType.INT64)
        nu = acc("num_updates", [1], VarType.INT64)
        block.append_op(
            "average_accumulates",
            inputs={"param": [param.name], "in_sum_1": [s1], "in_sum_2": [s2],
                    "in_sum_3": [s3], "in_num_accumulates": [na],
                    "in_old_num_accumulates": [ona], "in_num_updates": [nu]},
            outputs={"out_sum_1": [s1], "out_sum_2": [s2], "out_sum_3": [s3],
                     "out_num_accumulates": [na],
                     "out_old_num_accumulates": [ona],
                     "out_num_updates": [nu]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window,
                   OP_ROLE_KEY: OpRole.Optimize},
        )
        self._accums.append((param.name, s1, s2, s3, na, ona, nu))

    # ------------------------------------------------------------------
    def _averaged(self, scope, entry):
        import numpy as np

        _, s1, s2, s3, na, ona, _ = entry
        total = (np.asarray(scope.get(s1)) + np.asarray(scope.get(s2))
                 + np.asarray(scope.get(s3)))
        count = float(np.asarray(scope.get(na)).ravel()[0]
                      + np.asarray(scope.get(ona)).ravel()[0])
        return total / max(count, 1.0)

    def apply(self, executor=None, need_restore=True, scope=None):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            self._swap_in(scope)
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor, scope=scope)

        return _guard()

    def _resolve_scope(self, scope):
        if scope is not None:
            return scope
        from .framework.scope import global_scope

        return global_scope()

    def _swap_in(self, scope=None):
        scope = self._resolve_scope(scope)
        self._restore_vals = {}
        for entry in self._accums:
            pname = entry[0]
            if scope.get(entry[1]) is None:
                raise RuntimeError(
                    f"ModelAverage accumulators for {pname!r} not found in "
                    "the scope — pass the training scope via "
                    "apply(..., scope=your_scope) when not using the "
                    "global scope")
            self._restore_vals[pname] = scope.get(pname)
            scope.set(pname, self._averaged(scope, entry))

    def restore(self, executor=None, scope=None):
        if not self._restore_vals:
            return
        scope = self._resolve_scope(scope)
        for name, val in self._restore_vals.items():
            scope.set(name, val)
        self._restore_vals = None


# 2.0-style short aliases (reference: paddle.optimizer namespace)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adagrad = AdagradOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
Dpsgd = DpsgdOptimizer
LarsMomentum = LarsMomentumOptimizer
