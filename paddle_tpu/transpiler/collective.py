"""Collective transpilers: program rewrites inserting `c_*` collective ops.

Capability parity with reference: python/paddle/fluid/transpiler/
collective.py (Collective:36, GradAllReduce:178, LocalSGD:270) — rewrite a
single-trainer program into a multi-trainer collective program by
inserting c_broadcast of params into startup and c_allreduce_sum of grads
into main.  On TPU the rewritten program executes as ONE SPMD program
under shard_map (parallel/data_parallel.py) instead of N processes, and
the inserted ops lower to psum over the mesh axis.
"""
from __future__ import annotations

from ..backward import OP_ROLE_KEY, OP_ROLE_VAR_KEY, OpRole


class Collective:
    """reference: transpiler/collective.py:36."""

    def __init__(self, nrings: int = 1):
        self.nrings = nrings
        self.nranks = 1
        self.rank = 0

    def transpile(self, startup_program, main_program, rank=0, endpoints=None,
                  current_endpoint=None, wait_port=True, nranks=None):
        endpoints = endpoints or ["127.0.0.1:6170"]
        self.nranks = nranks if nranks is not None else len(endpoints)
        self.rank = rank
        self.startup_program = startup_program
        self.main_program = main_program
        self._transpile_startup_program()
        self._transpile_main_program()
        return main_program

    # ------------------------------------------------------------------
    def _transpile_startup_program(self):
        """Insert comm-init (ring -> mesh axis registration) and param
        broadcast (a no-op under replicated shardings, kept for program
        parity with reference collective.py:90-176)."""
        block = self.startup_program.global_block()
        for ring_id in range(self.nrings):
            block.append_op(
                "c_comm_init_all",
                attrs={"ring_id": ring_id, "nranks": self.nranks,
                       OP_ROLE_KEY: OpRole.Forward},
            )

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """reference: transpiler/collective.py:178 — allreduce-sum every grad
    between backward and optimize, scaled by 1/nranks.

    Hierarchical mode (reference: fleet's use_hierarchical_allreduce +
    multi_devices_graph_pass hierarchical rings) decomposes each flat
    allreduce into intra-group reduce_scatter -> inter-group allreduce ->
    intra-group allgather over a 2-D (inter, intra) mesh, so the heavy
    traffic rides the fast intra axis (ICI) and only 1/intra_nranks of
    the bytes cross the slow inter axis (DCN)."""

    INTRA_RING = 0
    INTER_RING = 1

    def __init__(self, nrings: int = 1, hierarchical: bool = False,
                 intra_nranks: int = 8):
        super().__init__(nrings)
        self.hierarchical = hierarchical
        self.intra_nranks = intra_nranks

    def _transpile_startup_program(self):
        if not self.hierarchical:
            return super()._transpile_startup_program()
        block = self.startup_program.global_block()
        block.append_op(
            "c_comm_init_all",
            attrs={"ring_id": self.INTRA_RING, "axis_name": "intra",
                   "nranks": self.intra_nranks, OP_ROLE_KEY: OpRole.Forward})
        block.append_op(
            "c_comm_init_all",
            attrs={"ring_id": self.INTER_RING, "axis_name": "inter",
                   "nranks": self.nranks // self.intra_nranks,
                   OP_ROLE_KEY: OpRole.Forward})

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        # find grads via op_role_var on optimize ops (reference :205)
        grad_names = []
        first_opt_idx = None
        for i, op_ in enumerate(block.ops):
            role = op_.attr(OP_ROLE_KEY, 0)
            if role == OpRole.Optimize or role == (OpRole.Optimize | OpRole.LRSched):
                if first_opt_idx is None:
                    first_opt_idx = i
                rv = op_.attr(OP_ROLE_VAR_KEY)
                if rv and len(rv) == 2:
                    grad_names.append(rv[1])
        if first_opt_idx is None or not grad_names:
            return
        ring = 0
        insert_at = first_opt_idx
        for g in grad_names:
            block._insert_op(
                insert_at, "scale",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"scale": 1.0 / self.nranks, OP_ROLE_KEY: OpRole.Backward},
            )
            insert_at += 1
            if self.hierarchical:
                insert_at = self._insert_hierarchical(block, insert_at, g)
            else:
                block._insert_op(
                    insert_at, "c_allreduce_sum",
                    inputs={"X": [g]}, outputs={"Out": [g]},
                    attrs={"ring_id": ring % self.nrings,
                           OP_ROLE_KEY: OpRole.Backward},
                )
                insert_at += 1
            ring += 1
        # c_sync_comm_stream before first optimizer op (API parity; no-op)
        block._insert_op(
            insert_at, "c_sync_comm_stream",
            inputs={"X": grad_names}, outputs={"Out": grad_names},
            attrs={"ring_id": 0, OP_ROLE_KEY: OpRole.Backward},
        )

    def _insert_hierarchical(self, block, at, g):
        gvar = block._find_var_recursive(g)
        shape = list(gvar.shape) if gvar is not None else []
        divisible = bool(shape) and shape[0] > 0 and \
            shape[0] % self.intra_nranks == 0
        if divisible:
            # bandwidth-optimal: RS(intra) -> AR(inter) -> AG(intra)
            from ..framework import unique_name

            shard = unique_name.generate(g + "@HIER_SHARD")
            block.create_var(name=shard, dtype=gvar.dtype,
                             shape=[shape[0] // self.intra_nranks] + shape[1:])
            block._insert_op(
                at, "c_reducescatter",
                inputs={"X": [g]}, outputs={"Out": [shard]},
                attrs={"ring_id": self.INTRA_RING, "nranks": self.intra_nranks,
                       OP_ROLE_KEY: OpRole.Backward})
            block._insert_op(
                at + 1, "c_allreduce_sum",
                inputs={"X": [shard]}, outputs={"Out": [shard]},
                attrs={"ring_id": self.INTER_RING, OP_ROLE_KEY: OpRole.Backward})
            block._insert_op(
                at + 2, "c_allgather",
                inputs={"X": [shard]}, outputs={"Out": [g]},
                attrs={"ring_id": self.INTRA_RING, "nranks": self.intra_nranks,
                       OP_ROLE_KEY: OpRole.Backward})
            return at + 3
        # fallback: two-stage allreduce (reduce intra then across groups)
        block._insert_op(
            at, "c_allreduce_sum",
            inputs={"X": [g]}, outputs={"Out": [g]},
            attrs={"ring_id": self.INTRA_RING, OP_ROLE_KEY: OpRole.Backward})
        block._insert_op(
            at + 1, "c_allreduce_sum",
            inputs={"X": [g]}, outputs={"Out": [g]},
            attrs={"ring_id": self.INTER_RING, OP_ROLE_KEY: OpRole.Backward})
        return at + 2


class LocalSGD(Collective):
    """reference: transpiler/collective.py:270 — train locally, average
    params over the ring every k steps.  TPU version: insert param
    averaging (allreduce * 1/nranks) after the optimizer ops; the k-step
    period is handled by running the averaging subprogram every k-th
    iteration (stored in attrs for the executor)."""

    def __init__(self, nrings: int = 1, k_steps: int = 1):
        super().__init__(nrings)
        self.k_steps = k_steps

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        params = []
        for op_ in block.ops:
            role = op_.attr(OP_ROLE_KEY, 0)
            if role == OpRole.Optimize:
                rv = op_.attr(OP_ROLE_VAR_KEY)
                if rv and len(rv) == 2:
                    params.append(rv[0])
        for p in params:
            block.append_op(
                "scale", inputs={"X": [p]}, outputs={"Out": [p]},
                attrs={"scale": 1.0 / self.nranks, OP_ROLE_KEY: OpRole.Optimize},
            )
            block.append_op(
                "c_allreduce_sum", inputs={"X": [p]}, outputs={"Out": [p]},
                attrs={"ring_id": 0, OP_ROLE_KEY: OpRole.Optimize,
                       "k_steps": self.k_steps},
            )
