from .collective import GradAllReduce, LocalSGD, Collective
from .distribute_transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
