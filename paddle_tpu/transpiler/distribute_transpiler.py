"""Parameter-server DistributeTranspiler: graph rewrite for PS training.

Capability parity with reference: python/paddle/fluid/transpiler/
distribute_transpiler.py (transpile:544 — split params/grads into blocks
across pservers, rewrite grads->send + params<-recv; get_pserver_program
:1150 — listen_and_serv + per-param optimize blocks; DistributedMode:68).

Round-1 scope: the full graph rewrite (the reference's cheap test tier,
test_dist_transpiler.py, asserts on op lists) + a host-side Python table
service for execution; the C++ gRPC table service lands with the PS
phase (SURVEY.md §7 phase 8).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

from ..framework.core import Program
from ..backward import OP_ROLE_KEY, OpRole


class DistributedMode:
    """reference: distribute_transpiler.py:68."""

    SYNC = 0
    ASYNC = 1
    HALF_ASYNC = 2
    GEO = 3


class DistributeTranspilerConfig:
    """reference: distribute_transpiler.py:141."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"
    print_log = False
    wait_port = True
    runtime_split_send_recv = False
    sync_mode = True
    half_async = False
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100

    @property
    def distributed_mode(self) -> int:
        """Map the config flags to a DistributedMode (reference:
        distribute_transpiler.py:68 + fleet DistributedStrategy modes)."""
        if self.geo_sgd_mode:
            return DistributedMode.GEO
        if self.half_async:
            return DistributedMode.HALF_ASYNC
        if not self.sync_mode:
            return DistributedMode.ASYNC
        return DistributedMode.SYNC

    def __init__(self):
        pass


class VarBlock:
    """reference: distribute_transpiler.py:80 — a slice of a var."""

    def __init__(self, varname, offset, size):
        self.varname = varname
        self.offset = offset
        self.size = size

    def __str__(self):
        return f"{self.varname}:{self.offset}:{self.size}"


def slice_variable(var_list, slice_count, min_block_size):
    """reference: distribute_transpiler.py slice_variable — even split of
    each var into at most slice_count blocks of >= min_block_size."""
    blocks = []
    for var in var_list:
        import numpy as np

        var_numel = int(np.prod([abs(s) for s in var.shape])) if var.shape else 1
        split_count = min(slice_count, max(1, var_numel // min_block_size))
        block_size = (var_numel + split_count - 1) // split_count
        # align to the trailing dim
        if len(var.shape) >= 2:
            dim1 = int(np.prod([abs(s) for s in var.shape[1:]]))
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = (var_numel + block_size - 1) // block_size
        for i in range(split_count):
            curr = min(block_size, var_numel - i * block_size)
            blocks.append(VarBlock(var.name, i, curr))
    return blocks


class DistributeTranspiler:
    """reference: distribute_transpiler.py:303."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._param_grads = []
        self._param_to_pserver: Dict[str, str] = {}

    def transpile(
        self,
        trainer_id: int,
        program: Optional[Program] = None,
        pservers: str = "127.0.0.1:6174",
        trainers: int = 1,
        sync_mode: bool = True,
        startup_program: Optional[Program] = None,
        current_endpoint: str = "127.0.0.1:6174",
        mode: Optional[int] = None,
    ):
        from ..framework.core import default_main_program, default_startup_program

        if mode is None:
            mode = self.config.distributed_mode
            # the sync_mode kwarg is the public API's mode switch and
            # must keep working on a default config
            if mode == DistributedMode.SYNC and not sync_mode:
                mode = DistributedMode.ASYNC
        self.mode = mode
        sync_mode = mode == DistributedMode.SYNC
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.pserver_endpoints = pservers.split(",")

        block = self.origin_program.global_block()
        # collect (param, grad) via op_role_var on optimize ops.  For
        # SYNC/ASYNC/HALF_ASYNC the optimizer ops move to the pservers;
        # for GEO they STAY — the trainer optimizes locally and the
        # communicator ships param deltas (communicator.h:383).
        param_grads = []
        opt_op_idxs = []
        for i, op_ in enumerate(block.ops):
            if op_.attr(OP_ROLE_KEY, 0) == OpRole.Optimize:
                rv = op_.attr("op_role_var")
                if rv and len(rv) == 2:
                    param_grads.append((rv[0], rv[1]))
                opt_op_idxs.append(i)
        self._param_grads = param_grads
        self._opt_ops = [block.ops[i] for i in opt_op_idxs]
        if mode != DistributedMode.GEO:
            for i in reversed(opt_op_idxs):
                block._remove_op(i)

        # -- distributed sparse embeddings (reference: distribute_
        # transpiler.py:1761 _replace_lookup_table_op_with_prefetch):
        # lookup_table ops whose table was built with is_distributed=True
        # become remote pulls against the PS sparse table, their grad ops
        # become sparse pushes, and the table leaves the dense param set.
        self._sparse_tables: Dict[str, int] = {}
        for op_ in block.ops:
            if op_.type == "lookup_table" and op_.input("W"):
                wname = op_.input("W")[0]
                wvar = block._find_var_recursive(wname)
                if wvar is not None and (
                        getattr(wvar, "is_distributed", False)
                        or op_.attr("is_distributed", False)):
                    self._sparse_tables[wname] = int(wvar.shape[-1])
        if self._sparse_tables:
            from collections import OrderedDict

            grad_suffix = "@GRAD"
            for op_ in block.ops:
                if op_.type == "lookup_table" and \
                        op_.input("W")[0] in self._sparse_tables:
                    wname = op_.input("W")[0]
                    op_.type = "distributed_lookup_table"
                    op_.inputs = OrderedDict({"Ids": list(op_.input("Ids"))})
                    op_.outputs = OrderedDict(
                        {"Outputs": list(op_.output("Out"))})
                    op_.attrs = {"table_name": wname,
                                 "emb_dim": self._sparse_tables[wname],
                                 OP_ROLE_KEY: OpRole.Forward}
                elif op_.type in ("lookup_table_grad",
                                  "lookup_table_sparse_grad") and \
                        op_.input("W") and \
                        op_.input("W")[0] in self._sparse_tables:
                    wname = op_.input("W")[0]
                    out_grads = []
                    for slot, names in op_.inputs.items():
                        if slot.endswith(grad_suffix):
                            out_grads = list(names)
                    op_.type = "distributed_lookup_table_grad"
                    op_.inputs = OrderedDict({
                        "Ids": list(op_.input("Ids")),
                        "Outputs" + grad_suffix: out_grads,
                    })
                    op_.outputs = OrderedDict()
                    op_.attrs = {"table_name": wname,
                                 "emb_dim": self._sparse_tables[wname],
                                 OP_ROLE_KEY: OpRole.Backward}
            # merge per-slot remote ops into ONE multi-Ids op per table
            # (reference: parameter_prefetch.cc batches one RPC per
            # table section; r5 — each host op between jit segments is a
            # device sync, and through a real accelerator link that sync
            # is a round-trip, so 2×n_slots ops/step became the
            # wide_deep PS bottleneck).  Forward ops merge into the
            # group's FIRST position (Ids are data/early vars — gated
            # below), grad ops into the LAST (all upstream grads ready).
            self._merge_lookup_ops(block, "distributed_lookup_table")
            self._merge_lookup_ops(block, "distributed_lookup_table_grad")
            # drop the grad accumulators for sparse tables (the backward
            # pass sums multi-consumer W@GRAD contributions — remote
            # pushes made them dead, and their @RENAME inputs are gone)
            dead_prefixes = tuple(f"{t}@GRAD" for t in self._sparse_tables)
            for i in reversed(range(len(block.ops))):
                outs = block.ops[i].output_arg_names
                if outs and all(o.startswith(dead_prefixes) for o in outs):
                    block._remove_op(i)
            # the table itself lives only on the pservers now: drop its
            # local init (the reference deletes the var from trainer
            # programs so a 1e8-row table never materializes host-side)
            sparse_and_grads = set(self._sparse_tables) | {
                n for n in block.vars
                if n.startswith(dead_prefixes)}
            sblock = self.startup_program.global_block()
            for i in reversed(range(len(sblock.ops))):
                outs = sblock.ops[i].output_arg_names
                if outs and all(o in self._sparse_tables for o in outs):
                    sblock._remove_op(i)
            for name in self._sparse_tables:
                sblock.vars.pop(name, None)
            for name in sparse_and_grads:
                block.vars.pop(name, None)
            param_grads = [(p, g) for (p, g) in param_grads
                           if p not in self._sparse_tables]
            self._param_grads = param_grads

        # round-robin assign params to pservers (reference uses RoundRobin)
        eps = self.pserver_endpoints
        self._ep_params: Dict[str, List[str]] = {ep: [] for ep in eps}
        self._ep_grads: Dict[str, List[str]] = {ep: [] for ep in eps}
        for i, (p, g) in enumerate(param_grads):
            ep = eps[i % len(eps)]
            self._param_to_pserver[p] = ep
            self._ep_params[ep].append(p)
            self._ep_grads[ep].append(g)

        if mode == DistributedMode.GEO:
            # GEO: the trainer program keeps its optimizer ops; a single
            # geo_sgd host op per step counts rounds and, every
            # geo_sgd_need_push_nums steps, pushes param deltas + pulls
            # the merged globals (communicator.h:383 GeoSgdCommunicator).
            # Params are listed as inputs AND outputs so the executor's
            # state analysis threads the refreshed values back to scope.
            ps = [p for p, g in param_grads]
            block.append_op(
                "geo_sgd",
                inputs={"X": ps},
                outputs={"Out": ps},
                attrs={"endpoints": eps,
                       "push_nums": self.config.geo_sgd_need_push_nums,
                       OP_ROLE_KEY: OpRole.RPC},
            )
            return

        # rewrite trainer program: send grads, recv params
        for i, (p, g) in enumerate(param_grads):
            ep = self._param_to_pserver[p]
            block.append_op(
                "send",
                inputs={"X": [g]},
                attrs={"epmap": [ep], "send_varnames": [g],
                       "table_name": p,
                       "sync_mode": sync_mode, OP_ROLE_KEY: OpRole.RPC},
            )
        if sync_mode:
            block.append_op(
                "send_barrier",
                attrs={"endpoints": eps, "trainer_id": trainer_id,
                       OP_ROLE_KEY: OpRole.RPC},
            )
        for j, (p, g) in enumerate(param_grads):
            ep = self._param_to_pserver[p]
            attrs = {"epmap": [ep], "recv_varnames": [p],
                     "table_name": p,
                     "sync_mode": sync_mode, OP_ROLE_KEY: OpRole.RPC}
            if j == 0 and mode == DistributedMode.HALF_ASYNC:
                # per-round barrier before the first pull of the next
                # round (HalfAsyncCommunicator::Barrier)
                attrs["half_async_barrier"] = True
            block.append_op("recv", outputs={"Out": [p]}, attrs=attrs)
        if sync_mode:
            block.append_op(
                "fetch_barrier",
                attrs={"endpoints": eps, "trainer_id": trainer_id,
                       OP_ROLE_KEY: OpRole.RPC},
            )

    # ------------------------------------------------------------------
    @staticmethod
    def _merge_lookup_ops(block, op_type):
        """Merge ALL ``op_type`` ops in the block into ONE multi-Ids op
        with per-slot table_names/emb_dims attrs, so the whole sparse
        side costs one host-op device sync and one (thread-fanned) RPC
        round per step instead of one per slot per table — through a
        real accelerator link every host op between jit segments is a
        blocking round-trip, and those dominate the PS step.  The
        forward merges into the first member's position only if every
        later member's Ids is a data var or defined before it; the grad
        merges into the last member (all grads ready)."""
        idxs = [i for i, op_ in enumerate(block.ops) if op_.type == op_type]
        if len(idxs) < 2:
            return
        is_fwd = op_type == "distributed_lookup_table"
        grad_slot = "Outputs@GRAD"
        keep = idxs[0] if is_fwd else idxs[-1]
        if is_fwd:
            defined = set()
            for j in range(keep):
                defined.update(block.ops[j].output_arg_names)

            def _ready(i):
                for n in block.ops[i].input("Ids"):
                    v = block._find_var_recursive(n)
                    if n not in defined and not (
                            v is not None and getattr(v, "is_data", False)):
                        return False
                return True

            # merge only the ops whose Ids exist at the keep position;
            # an op with later-computed Ids stays standalone instead of
            # aborting the whole merge
            idxs = [i for i in idxs if _ready(i)]
            if len(idxs) < 2 or keep not in idxs:
                return
        keep_op = block.ops[keep]
        ids, outs, tables, dims = [], [], [], []
        for i in idxs:
            o = block.ops[i]
            o_ids = list(o.input("Ids"))
            ids.extend(o_ids)
            outs.extend(o.output("Outputs") if is_fwd
                        else o.input(grad_slot))
            tables.extend([o.attr("table_name")] * len(o_ids))
            dims.extend([int(o.attr("emb_dim"))] * len(o_ids))
        keep_op.inputs["Ids"] = ids
        if is_fwd:
            keep_op.outputs["Outputs"] = outs
        else:
            keep_op.inputs[grad_slot] = outs
        keep_op.attrs["table_names"] = tables
        keep_op.attrs["emb_dims"] = dims
        for i in sorted(idxs, reverse=True):
            if i != keep:
                block._remove_op(i)

    def get_trainer_program(self, wait_port=True) -> Program:
        return self.origin_program

    def get_pserver_program(self, endpoint: str) -> Program:
        """Build the pserver program: listen_and_serv wrapping per-param
        optimize blocks (reference: get_pserver_program:1150)."""
        prog = Program()
        block = prog.global_block()
        params = self._ep_params.get(endpoint, [])
        grads = self._ep_grads.get(endpoint, [])
        src_block = self.origin_program.global_block()
        for p in params:
            v = src_block._find_var_recursive(p)
            if v is not None:
                block.create_var(name=p, shape=v.shape, dtype=v.dtype,
                                 persistable=True)
        for g in grads:
            v = src_block._find_var_recursive(g)
            if v is not None:
                block.create_var(name=g, shape=v.shape, dtype=v.dtype)
        # per-param optimize sub-blocks
        opt_block_ids = []
        for p, g in zip(params, grads):
            sub = prog._create_block(parent_idx=0)
            for op_ in self._opt_ops:
                rv = op_.attr("op_role_var")
                if rv and rv[0] == p:
                    sub.ops.append(op_)
            opt_block_ids.append(sub.idx)
            prog._rollback()
        block.append_op(
            "listen_and_serv",
            attrs={
                "endpoint": endpoint,
                "optimize_blocks": opt_block_ids,
                "grad_to_params": dict(zip(grads, params)),
                "sync_mode": self.sync_mode,
                "Fanin": self.trainer_num,
                OP_ROLE_KEY: OpRole.RPC,
            },
        )
        return prog

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None) -> Program:
        return self.startup_program

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), self.get_startup_program(endpoint)
