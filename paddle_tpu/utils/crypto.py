"""Encrypted model save/load — AES-CTR cipher.

Reference: paddle/fluid/framework/io/crypto/ (AESCipher, CipherFactory,
CipherUtils — pybind/crypto.cc exposes CipherUtils.gen_key /
Cipher.encrypt/decrypt(+_to_file/_from_file)).  The block cipher itself
is native C++ (native/crypto.cpp, FIPS-197), bound here via ctypes; key
material stays host-side.

Ciphertext layout: 16-byte random IV || CTR stream.  An HMAC-less CTR
matches the reference's AES cipher shape (confidentiality, not
authentication).
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

__all__ = ["CipherUtils", "CipherFactory", "AESCipher"]

_lib = None


def _load():
    global _lib
    if _lib is None:
        from ..native.build import load_library

        _lib = load_library("crypto")
        _lib.PD_AesCtrCrypt.restype = ctypes.c_int
        _lib.PD_AesCtrCrypt.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_ubyte),
            ctypes.c_uint64]
        _lib.PD_AesEncryptBlock.restype = ctypes.c_int
        _lib.PD_AesEncryptBlock.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_ubyte)]
    return _lib


class CipherUtils:
    """reference: io/crypto/cipher_utils.h CipherUtils."""

    @staticmethod
    def gen_key(length_bits: int = 128) -> bytes:
        if length_bits not in (128, 192, 256):
            raise ValueError("key length must be 128/192/256 bits")
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, path: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        with open(path, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()


class AESCipher:
    """reference: io/crypto/aes_cipher.h AESCipher (CTR mode)."""

    def __init__(self, key: Optional[bytes] = None):
        self._key = key

    def _crypt(self, data: bytes, key: bytes, iv: bytes) -> bytes:
        lib = _load()
        out = (ctypes.c_ubyte * len(data))()
        rc = lib.PD_AesCtrCrypt(key, len(key), iv, data, out, len(data))
        if rc != 0:
            raise ValueError(f"bad AES key length: {len(key)} bytes")
        return bytes(out)

    def encrypt(self, plaintext: bytes, key: Optional[bytes] = None) -> bytes:
        key = key or self._key
        iv = os.urandom(16)
        return iv + self._crypt(plaintext, key, iv)

    def decrypt(self, ciphertext: bytes,
                key: Optional[bytes] = None) -> bytes:
        key = key or self._key
        if len(ciphertext) < 16:
            raise ValueError("ciphertext too short (missing IV)")
        iv, body = ciphertext[:16], ciphertext[16:]
        return self._crypt(body, key, iv)

    def encrypt_to_file(self, plaintext: bytes, key: bytes, path: str):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read(), key)


class CipherFactory:
    """reference: io/crypto/cipher.h CipherFactory::CreateCipher."""

    @staticmethod
    def create_cipher(config_file: Optional[str] = None) -> AESCipher:
        return AESCipher()


def _aes_encrypt_block(key: bytes, block: bytes) -> bytes:
    """Single-block forward cipher (test hook for FIPS-197 vectors)."""
    lib = _load()
    out = (ctypes.c_ubyte * 16)()
    rc = lib.PD_AesEncryptBlock(key, len(key), block, out)
    if rc != 0:
        raise ValueError("bad key length")
    return bytes(out)
