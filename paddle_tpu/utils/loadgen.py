"""Open-loop load generation + latency reporting for the serving bench.

Shared by tools/serving_bench.py and tools/serving_ab.py so the
serving numbers join the bench trajectory with ONE report format
(the stable one-line JSON convention bench.py established).

Open-loop means arrivals are a Poisson process fixed in advance by a
seed — the generator never waits for the system (closed-loop load
hides queueing collapse: a slow server slows its own offered load).
The driver replays the trace against an engine exposing
``submit(request)`` / ``step(now)`` / ``has_work()`` (both
ServingEngine and StaticBatchingEngine do), stamping real wall-clock
times on every emitted token.
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Dict, List, Sequence

import numpy as np

__all__ = ["poisson_trace", "replay_trace", "latency_report",
           "per_request_latency", "emit_json", "pct"]


@dataclass(frozen=True)
class TraceEntry:
    req_id: int
    arrival: float
    prompt: List[int]
    max_new_tokens: int


def poisson_trace(num_requests: int, rate: float, vocab_size: int,
                  prompt_len_range=(4, 32), max_new_range=(4, 32),
                  seed: int = 0, prefix_len: int = 0,
                  prefix_share: float = 0.0,
                  repeat_frac: float = 0.0) -> List[TraceEntry]:
    """Seeded open-loop trace: exponential inter-arrivals at ``rate``
    req/s, uniform prompt lengths and output budgets.  The same seed
    yields the same trace for every engine under test (the A/B
    contract).

    ``prefix_len`` > 0 arms the SHARED-PREFIX workload (the dominant
    real-traffic pattern: system prompts / few-shot headers): one
    seeded common prefix of that many tokens is prepended to each
    request's own suffix with probability ``prefix_share`` — the trace
    the CoW prefix cache is measured on.  ``prefix_len=0`` (default)
    reproduces the exact pre-r19 trace for every seed (the RNG draw
    order is unchanged).

    ``repeat_frac`` > 0 arms the SELF-SIMILAR workload (code,
    templated text, retry storms): each prompt is rewritten so roughly
    that fraction of its tokens repeat an n-gram drawn from earlier in
    the same prompt — the trace the n-gram prompt-lookup drafter
    (inference/spec_decode.py) gets its acceptance from, per the
    prompt-lookup-decoding observation that generated continuations of
    repeated spans mostly copy their earlier continuation.  Like the
    prefix knobs it draws from a DERIVED seed, so ``repeat_frac=0``
    (default) is bit-identical to the pre-r21 trace for every seed
    (pinned by test)."""
    rng = np.random.RandomState(seed)
    prefix: List[int] = []
    if prefix_len > 0:
        # drawn from a DERIVED seed so arming the prefix knobs never
        # perturbs the per-request draws below
        prefix = np.random.RandomState(seed + 7919).randint(
            0, vocab_size, size=prefix_len).astype(int).tolist()
    rep_rng = np.random.RandomState(seed + 6007) if repeat_frac > 0 else None
    t = 0.0
    out = []
    for i in range(num_requests):
        t += float(rng.exponential(1.0 / rate))
        n = int(rng.randint(prompt_len_range[0], prompt_len_range[1] + 1))
        m = int(rng.randint(max_new_range[0], max_new_range[1] + 1))
        prompt = rng.randint(0, vocab_size, size=n).astype(int).tolist()
        if prefix and rng.random_sample() < prefix_share:
            prompt = prefix + prompt
        if rep_rng is not None and len(prompt) >= 4:
            # splice copies of earlier spans over ~repeat_frac of the
            # prompt tail (length preserved; arrival/length draws above
            # came from the primary stream, untouched)
            budget = int(round(repeat_frac * len(prompt)))
            pos = max(2, len(prompt) - budget)
            while pos < len(prompt):
                src = int(rep_rng.randint(0, pos - 1))
                span = int(rep_rng.randint(2, 5))
                span = min(span, len(prompt) - pos, pos - src)
                prompt[pos:pos + span] = prompt[src:src + span]
                pos += span
        out.append(TraceEntry(i, t, prompt, m))
    return out


def replay_trace(engine, trace: Sequence[TraceEntry],
                 request_cls=None) -> Dict:
    """Drive ``engine`` with the trace open-loop: requests are submitted
    when their arrival time passes (wall clock, time-shifted to start
    now); the engine steps continuously while it has work or arrivals
    remain.  Returns raw measurements for :func:`latency_report`."""
    if request_cls is None:
        from ..inference.serving import Request as request_cls  # noqa: N806
    reqs = {e.req_id: request_cls(e.req_id, list(e.prompt),
                                  e.max_new_tokens, e.arrival)
            for e in trace}
    pending = sorted(trace, key=lambda e: (e.arrival, e.req_id))
    t0 = time.perf_counter()
    token_times: Dict[int, List[float]] = {e.req_id: [] for e in trace}
    pool_util: List[float] = []
    i = 0
    while i < len(pending) or engine.has_work():
        now = time.perf_counter() - t0
        while i < len(pending) and pending[i].arrival <= now:
            engine.submit(reqs[pending[i].req_id])
            i += 1
        if not engine.has_work():
            if i < len(pending):  # idle until the next arrival
                time.sleep(min(pending[i].arrival - now, 0.05))
            continue
        for ev in engine.step(now):
            token_times[ev.req_id].append(ev.time)
        kv = getattr(engine, "kv", None) or getattr(
            getattr(engine, "core", None), "kv", None)
        if kv is not None:
            pool_util.append(kv.utilization())
    elapsed = time.perf_counter() - t0
    return {
        "requests": reqs,
        "token_times": token_times,
        "elapsed_s": elapsed,
        "pool_utilization": pool_util,
    }


def pct(xs: List[float], q: float) -> float:
    """Percentile with the empty-list NaN convention every serving
    report shares (serving_bench and serving_ab)."""
    return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")


def latency_report(raw: Dict) -> Dict:
    """tokens/s + per-token latency percentiles from a replay.

    Per-token latency is the request-level inter-token gap (first token
    measured from arrival — TTFT folds into the same distribution the
    way per-token SLOs are usually quoted); preempted-and-restarted
    requests contribute their FINAL run's tokens only (out_tokens is
    reset on preemption), so a preemption shows up as a long gap, not a
    double count."""
    reqs = raw["requests"]
    gaps: List[float] = []
    ttft: List[float] = []
    total_tokens = 0
    for rid, times in raw["token_times"].items():
        req = reqs[rid]
        n_final = len(req.out_tokens)
        times = times[-n_final:] if n_final else []
        total_tokens += len(times)
        prev = req.arrival_time
        for j, t in enumerate(times):
            gaps.append(t - prev)
            if j == 0:
                ttft.append(t - req.arrival_time)
            prev = t
    # a shed request (admission.py slo_aware policy) is a TERMINAL
    # outcome, not a hang: it leaves "unfinished" and is counted on its
    # own line (fifo traces: shed == 0, unfinished unchanged)
    shed = sum(1 for r in reqs.values()
               if getattr(r, "shed_at", None) is not None)
    unfinished = sum(1 for r in reqs.values()
                     if r.finished_at is None
                     and getattr(r, "shed_at", None) is None)
    util = raw["pool_utilization"]
    return {
        "num_requests": len(reqs),
        "unfinished": unfinished,
        "shed": shed,
        "total_tokens": total_tokens,
        "elapsed_s": round(raw["elapsed_s"], 4),
        "tokens_per_s": round(total_tokens / max(raw["elapsed_s"], 1e-9), 2),
        "p50_token_latency_s": round(pct(gaps, 50), 5),
        "p99_token_latency_s": round(pct(gaps, 99), 5),
        "p50_ttft_s": round(pct(ttft, 50), 5),
        "kv_util_mean": round(float(np.mean(util)), 4) if util else 0.0,
        "kv_util_peak": round(float(np.max(util)), 4) if util else 0.0,
    }


def per_request_latency(raw: Dict) -> Dict:
    """Per-request TTFT + decode gaps from a replay — the INDEPENDENT
    per-request view the online SLO tracker (utils/telemetry.py
    SLOTracker) is reconciled against (tools/slo_report.py --quick):
    same final-run convention as :func:`latency_report` (preempted
    runs' tokens retroactively dropped, first gap from arrival)."""
    out: Dict = {}
    for rid, times in raw["token_times"].items():
        req = raw["requests"][rid]
        n_final = len(req.out_tokens)
        times = times[-n_final:] if n_final else []
        gaps, prev = [], req.arrival_time
        for t in times:
            gaps.append(t - prev)
            prev = t
        out[rid] = {
            "ttft_s": gaps[0] if gaps else float("nan"),
            "decode_gaps": gaps[1:],
            "tokens": len(times),
            "finished": req.finished_at is not None,
            "shed": getattr(req, "shed_at", None) is not None,
            "preemptions": req.preemptions,
        }
    return out


def emit_json(tag: str, payload: Dict) -> str:
    """The stable one-line ``TAG={json}`` convention bench.py uses —
    greppable by the driver, diffable across rounds."""
    line = tag + "=" + json.dumps(payload, sort_keys=True)
    print(line)
    return line
