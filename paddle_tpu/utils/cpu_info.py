"""Host CPU/device info helpers.

Reference: paddle/fluid/platform/cpu_info.cc (CpuTotalPhysicalMemory,
CpuMaxAllocSize, CpuMinChunkSize, CpuMaxChunkSize) and device info
queries.  The host side here only feeds input pipelines and the PS
runtime — XLA owns device memory — so these report host facts plus the
attached accelerator inventory.
"""
from __future__ import annotations

import os

from . import flags


def cpu_count() -> int:
    return os.cpu_count() or 1


def cpu_total_physical_memory() -> int:
    try:
        return (os.sysconf("SC_PHYS_PAGES")
                * os.sysconf("SC_PAGE_SIZE"))
    except (ValueError, OSError, AttributeError):
        return 4 << 30


def cpu_max_alloc_size() -> int:
    """reference: cpu_info.cc:70 — total memory scaled by
    FLAGS_fraction_of_cpu_memory_to_use."""
    frac = float(flags._flags.get("FLAGS_fraction_of_cpu_memory_to_use",
                                  1.0))
    return int(frac * cpu_total_physical_memory())


def cpu_min_chunk_size() -> int:
    return 1 << 12  # 4 KiB, reference cpu_info.cc:76


def cpu_max_chunk_size() -> int:
    frac = float(flags._flags.get(
        "FLAGS_initial_cpu_memory_in_mb", 500))
    return min(int(frac) << 20, cpu_max_alloc_size())


def device_count() -> int:
    """Attached accelerator count (jax devices)."""
    try:
        import jax

        return len(jax.devices())
    except Exception:
        return 0


def device_info() -> list:
    """Per-device kind/platform list (nvidia-smi/cudaGetDeviceProperties
    analog for the TPU world)."""
    try:
        import jax

        return [{"id": d.id, "kind": getattr(d, "device_kind", "unknown"),
                 "platform": d.platform} for d in jax.devices()]
    except Exception:
        return []
