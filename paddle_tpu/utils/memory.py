"""Memory/allocator statistics shim (SURVEY §2.9 #9) + the measured
half of the r15 memory-observability layer.

Reference: paddle/fluid/memory/allocation/allocator_facade.h and the
stat surface behind FLAGS_fraction_of_gpu_memory_to_use.  On TPU the
allocator is XLA's BFC — we expose its PJRT per-device statistics when
the backend reports them, and fall back to an exact census of this
client's live device arrays otherwise (the tunnel/CPU backends do not
export allocator counters).

The live-arrays census is **shard-aware** (r15): a replicated array
contributes its full bytes to every device it lives on, but a
``P('dp')``-sharded array contributes only the shard bytes actually
resident on the queried device — so the census agrees with the static
planner's per-device model (framework/memory_plan.py) across the ZeRO
ladder instead of over-counting sharded state ndev times.

:class:`PeakTracker` is the per-step measured-peak half of the
modeled-vs-measured reconciliation ``tools/mem_report.py`` prints: on
chip it reads ``peak_bytes_in_use`` from the PJRT allocator; on the
CPU proxy it max-tracks the live-arrays census across ``sample()``
calls (a proxy — blind to XLA scratch between samples, which is
exactly why the tool prints both numbers side by side instead of
pretending they are the same quantity).
"""
from __future__ import annotations

from typing import Dict, Optional


def _device_shard_bytes(arr, dev) -> int:
    """Bytes of ``arr`` actually resident on ``dev``: the sum of its
    addressable shards placed there (full nbytes for single-device /
    replicated entries, the row-block for P('dp') layouts)."""
    try:
        shards = arr.addressable_shards
    except Exception:
        shards = None
    if shards:
        total = 0
        for s in shards:
            if s.device == dev:
                total += int(s.data.nbytes)
        return total
    try:
        arr_devs = arr.devices() if callable(getattr(arr, "devices", None)) \
            else {getattr(arr, "device", None)}
    except Exception:
        return 0
    return int(arr.nbytes) if dev in arr_devs else 0


def live_arrays_bytes(device_id: int = 0) -> Dict[str, int]:
    """Shard-aware census of this client's live jax.Arrays on one
    device: exact for framework-held buffers, blind to XLA
    scratch/temporaries."""
    import jax

    devs = jax.devices()
    if device_id >= len(devs):
        raise ValueError(f"device {device_id} not present ({len(devs)} found)")
    dev = devs[device_id]
    total = 0
    count = 0
    for arr in jax.live_arrays():
        b = _device_shard_bytes(arr, dev)
        if b:
            total += b
            count += 1
    return {"bytes_in_use": total, "num_live_arrays": count,
            "source": "live_arrays"}


def memory_stats(device_id: int = 0) -> Dict[str, int]:
    """Allocator statistics for one device.

    Returns a dict with at least ``bytes_in_use`` and ``source``:
    * source="pjrt": the backend's own allocator counters
      (bytes_in_use, peak_bytes_in_use, bytes_limit, ... as reported).
    * source="live_arrays": shard-aware summed bytes of this client's
      live jax.Arrays resident on the device.
    """
    import jax

    devs = jax.devices()
    if device_id >= len(devs):
        raise ValueError(f"device {device_id} not present ({len(devs)} found)")
    dev = devs[device_id]
    stats: Optional[dict] = None
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats:
        out = {k: int(v) for k, v in stats.items()}
        out["source"] = "pjrt"
        return out
    return live_arrays_bytes(device_id)


def measured_peak(device_id: int = 0) -> Dict[str, int]:
    """Best-available measured peak for one device: the PJRT
    allocator's ``peak_bytes_in_use`` on chip, else the CURRENT
    live-arrays census (a floor, not a true peak — use
    :class:`PeakTracker` to max-track it across steps)."""
    s = memory_stats(device_id)
    if s["source"] == "pjrt":
        return {"peak_bytes": int(s.get("peak_bytes_in_use",
                                        s.get("bytes_in_use", 0))),
                "source": "pjrt"}
    return {"peak_bytes": int(s.get("bytes_in_use", 0)),
            "source": "live_arrays"}


class PeakTracker:
    """Per-step measured-peak snapshotter for the modeled-vs-measured
    reconciliation: call :meth:`sample` after each step (and wherever
    else residency may crest); :attr:`peak_bytes` holds the max seen.
    Publishes the ``hbm_measured_peak_bytes`` gauge alongside the
    compile paths' ``hbm_modeled_peak_bytes``."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id
        self.peak_bytes = 0
        self.samples = 0
        self.source = None

    def sample(self) -> int:
        m = measured_peak(self.device_id)
        self.samples += 1
        self.source = m["source"]
        if m["peak_bytes"] > self.peak_bytes:
            self.peak_bytes = int(m["peak_bytes"])
            from . import telemetry as tm

            tm.gauge("hbm_measured_peak_bytes",
                     "measured per-device HBM peak (pjrt allocator "
                     "counter on chip; live-arrays census max on the "
                     "CPU proxy)").set(self.peak_bytes)
        return self.peak_bytes

    def as_dict(self) -> dict:
        return {"peak_bytes": self.peak_bytes, "samples": self.samples,
                "source": self.source, "device": self.device_id}


def memory_summary(device_id: int = 0) -> str:
    """Human-readable one-liner for logs / the profiler report."""
    s = memory_stats(device_id)
    gb = s.get("bytes_in_use", 0) / (1 << 30)
    if s["source"] == "pjrt":
        peak = s.get("peak_bytes_in_use", 0) / (1 << 30)
        limit = s.get("bytes_limit", 0) / (1 << 30)
        return (f"device {device_id}: {gb:.3f} GiB in use "
                f"(peak {peak:.3f}, limit {limit:.3f}) [pjrt]")
    return (f"device {device_id}: {gb:.3f} GiB across "
            f"{s.get('num_live_arrays', 0)} live arrays [live_arrays]")
