"""Memory/allocator statistics shim (SURVEY §2.9 #9).

Reference: paddle/fluid/memory/allocation/allocator_facade.h and the
stat surface behind FLAGS_fraction_of_gpu_memory_to_use.  On TPU the
allocator is XLA's BFC — we expose its PJRT per-device statistics when
the backend reports them, and fall back to an exact census of this
client's live device arrays otherwise (the tunnel/CPU backends do not
export allocator counters).
"""
from __future__ import annotations

from typing import Dict, Optional


def memory_stats(device_id: int = 0) -> Dict[str, int]:
    """Allocator statistics for one device.

    Returns a dict with at least ``bytes_in_use`` and ``source``:
    * source="pjrt": the backend's own allocator counters
      (bytes_in_use, peak_bytes_in_use, bytes_limit, ... as reported).
    * source="live_arrays": summed nbytes of this client's live
      jax.Arrays on the device — exact for framework-held buffers, blind
      to XLA scratch/temporaries.
    """
    import jax

    devs = jax.devices()
    if device_id >= len(devs):
        raise ValueError(f"device {device_id} not present ({len(devs)} found)")
    dev = devs[device_id]
    stats: Optional[dict] = None
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats:
        out = {k: int(v) for k, v in stats.items()}
        out["source"] = "pjrt"
        return out
    total = 0
    count = 0
    for arr in jax.live_arrays():
        try:
            arr_devs = arr.devices() if callable(getattr(arr, "devices", None)) \
                else {getattr(arr, "device", None)}
        except Exception:
            continue
        if dev in arr_devs:
            total += int(arr.nbytes)
            count += 1
    return {"bytes_in_use": total, "num_live_arrays": count,
            "source": "live_arrays"}


def memory_summary(device_id: int = 0) -> str:
    """Human-readable one-liner for logs / the profiler report."""
    s = memory_stats(device_id)
    gb = s.get("bytes_in_use", 0) / (1 << 30)
    if s["source"] == "pjrt":
        peak = s.get("peak_bytes_in_use", 0) / (1 << 30)
        limit = s.get("bytes_limit", 0) / (1 << 30)
        return (f"device {device_id}: {gb:.3f} GiB in use "
                f"(peak {peak:.3f}, limit {limit:.3f}) [pjrt]")
    return (f"device {device_id}: {gb:.3f} GiB across "
            f"{s.get('num_live_arrays', 0)} live arrays [live_arrays]")
