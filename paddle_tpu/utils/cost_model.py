"""Analytic per-op cost model for comm-schedule decisions.

The measurement-driven half of the DP comm layer (reference intent:
PaddlePaddle's adaptive distributed training, arXiv:2112.02752 — cost
models drive the parallelization/communication plan instead of fixed
constants).  Two consumers:

* ``framework/ir.py fuse_all_reduce_pass`` under
  ``FLAGS_fuse_grad_size_in_MB=auto`` partitions the gradient-reduce
  entries into *variable-size* buckets by minimizing the modeled finish
  time of the serialized collective stream against the modeled backward
  timeline (each bucket's collective should finish roughly as the next
  bucket's last gradient becomes ready);
* ``tools/dp_comm_stats.py`` prints the timeline + modeled exposed-comm
  bytes so a schedule change is reviewable without a chip.

The model is deliberately coarse — max(FLOPs/peak, bytes/HBM-bw) per
compute op, a bidirectional-ring alpha-beta model per collective — and
its job is *relative* ordering of schedules, not absolute times.
``CostModel.calibrated`` rescales the compute rates so the modeled
backward matches one profiled step, which is all the bucket decision
needs (the comm/compute ratio).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence, Tuple

#: collectives + sync ops: excluded from the compute timeline (they ride
#: the comm stream the schedule is being built FOR)
COMM_OPS = frozenset({
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "allreduce", "c_fused_allreduce",
    "c_fused_reduce_scatter", "c_reducescatter", "c_allgather",
    "c_broadcast", "broadcast", "c_concat", "c_split", "alltoall",
    "c_sync_comm_stream", "c_sync_calc_stream", "c_wait_comm_stream",
    "c_wait_calc_stream", "barrier", "c_comm_init", "c_comm_init_all",
    "c_gen_nccl_id",
})

#: op type -> which input slots form a (lhs, rhs) GEMM; flops = 2*M*K*N.
#: Grad ops replay two GEMMs (dX and dW), covered by the multiplier.
_MATMUL_OPS: Dict[str, Tuple[str, str, float]] = {
    "mul": ("X", "Y", 1.0),
    "matmul": ("X", "Y", 1.0),
    "matmul_v2": ("X", "Y", 1.0),
    "fc": ("Input", "W", 1.0),
    "mul_grad": ("X", "Y", 2.0),
    "matmul_grad": ("X", "Y", 2.0),
    "matmul_v2_grad": ("X", "Y", 2.0),
}


@dataclass(frozen=True)
class CostModel:
    """Device constants for the analytic model.  Defaults approximate a
    single TPU core (the target the schedule ships to — on the CPU proxy
    only the *relative* schedule matters, which these preserve)."""

    flops_per_s: float = 9.0e13       # dense matmul peak
    hbm_bytes_per_s: float = 8.0e11   # memory-bound elementwise ops
    ici_bytes_per_s: float = 4.5e10   # per-chip ring bandwidth
    launch_s: float = 1.0e-6          # per-collective launch/latency
    assumed_batch: int = 64           # stands in for dynamic (-1) dims

    def calibrated(self, measured_backward_s: float,
                   modeled_backward_s: float) -> "CostModel":
        """Rescale compute rates so the modeled backward equals a
        profiled one; comm constants are hardware facts and stay."""
        if measured_backward_s <= 0 or modeled_backward_s <= 0:
            return self
        f = modeled_backward_s / measured_backward_s
        return replace(self, flops_per_s=self.flops_per_s * f,
                       hbm_bytes_per_s=self.hbm_bytes_per_s * f)


# ==========================================================================
# Measured-profile store (r13: the profiler -> autotune calibration loop)
# ==========================================================================
# ``profiler.disable_profiler`` publishes the measured executor step
# time here; ``default_cost_model`` consumes it so every autotune
# decision (framework/ir.py fuse_all_reduce_pass, tools/dp_comm_stats)
# runs on measured rates whenever a profile exists.  The version
# counter participates in the executor / DP compile-cache keys: a new
# profile may move bucket boundaries, so compiled programs keyed on the
# old rates must not be silently reused.
_PROFILE_LOCK = threading.Lock()
_PROFILE: Optional[dict] = None
_CAL_VERSION = 0


def set_measured_profile(step_s: float, per_op_s: Optional[Dict] = None,
                         source: str = ""):
    """Record one profiled step: ``step_s`` is the measured wall time of
    an ``executor_run`` (stands in for the backward horizon — the
    calibration only needs the comm/compute *ratio*), ``per_op_s``
    optionally carries per-event mean times for finer consumers."""
    global _PROFILE, _CAL_VERSION
    if not step_s or step_s <= 0:
        return
    with _PROFILE_LOCK:
        _PROFILE = {"step_s": float(step_s),
                    "per_op_s": dict(per_op_s or {}), "source": source}
        _CAL_VERSION += 1


def measured_profile() -> Optional[dict]:
    with _PROFILE_LOCK:
        return dict(_PROFILE) if _PROFILE is not None else None


def clear_measured_profile():
    global _PROFILE, _CAL_VERSION
    with _PROFILE_LOCK:
        if _PROFILE is not None:
            _PROFILE = None
            _CAL_VERSION += 1


def calibration_version() -> int:
    """Bumped on every profile set/clear — compile caches key on it."""
    with _PROFILE_LOCK:
        return _CAL_VERSION


def default_cost_model(ops: Optional[Sequence] = None,
                       block=None) -> "CostModel":
    """The cost model every schedule decision should start from: the
    hand-set defaults, rescaled against the measured profile when one
    exists (and a program is given to model against).  Without a
    profile this is exactly ``CostModel()`` — the pre-r13 behavior."""
    cm = CostModel()
    prof = measured_profile()
    if prof and ops is not None and block is not None:
        _, modeled = backward_timeline(ops, block, cm)
        cm = cm.calibrated(prof["step_s"], modeled)
    return cm


def _dims(block, name, assumed_batch) -> Optional[List[int]]:
    var = block._find_var_recursive(name)
    if var is None or var.shape is None:
        return None
    out = []
    for d in var.shape:
        if d is None:
            return None
        d = int(d)
        out.append(assumed_batch if d < 0 else d)
    return out


def _numel(dims) -> int:
    n = 1
    for d in dims:
        n *= max(d, 1)
    return n


def op_flops_bytes(op_, block, assumed_batch=64) -> Tuple[float, float]:
    """(flops, moved bytes) for one compute op.  GEMM-shaped ops get
    2*M*K*N flops; conv2d gets 2*out_elems*receptive-field; everything
    else is elementwise over its touched bytes (4 B/elem assumed — the
    model cares about ratios, not dtypes)."""
    touched = 0
    for names in list(op_.inputs.values()) + list(op_.outputs.values()):
        for n in names:
            if n == "@EMPTY@":
                continue
            dims = _dims(block, n, assumed_batch)
            if dims:
                touched += _numel(dims) * 4
    mm = _MATMUL_OPS.get(op_.type)
    if mm is not None:
        lhs_slot, rhs_slot, mult = mm
        lhs = op_.inputs.get(lhs_slot, [None])[0]
        rhs = op_.inputs.get(rhs_slot, [None])[0]
        ld = _dims(block, lhs, assumed_batch) if lhs else None
        rd = _dims(block, rhs, assumed_batch) if rhs else None
        if ld and rd and len(rd) >= 2:
            m = _numel(ld[:-1])
            k = ld[-1]
            n = rd[-1]
            return 2.0 * m * k * n * mult, float(touched)
    if op_.type in ("conv2d", "depthwise_conv2d", "conv2d_grad",
                    "depthwise_conv2d_grad"):
        out_slot = "Output" if "Output" in op_.outputs else "Out"
        out = op_.outputs.get(out_slot, [None])[0] or \
            op_.inputs.get(out_slot, [None])[0]
        fil = op_.inputs.get("Filter", [None])[0]
        od = _dims(block, out, assumed_batch) if out else None
        fd = _dims(block, fil, assumed_batch) if fil else None
        if od and fd and len(fd) == 4:
            mult = 2.0 if op_.type.endswith("_grad") else 1.0
            return (2.0 * _numel(od) * fd[1] * fd[2] * fd[3] * mult,
                    float(touched))
    return float(_numel([1])), float(touched)


def op_time_s(op_, block, cm: CostModel) -> float:
    flops, nbytes = op_flops_bytes(op_, block, cm.assumed_batch)
    return max(flops / cm.flops_per_s, nbytes / cm.hbm_bytes_per_s)


def backward_timeline(ops: Sequence, block, cm: CostModel
                      ) -> Tuple[List[float], float]:
    """Cumulative modeled completion time per op index (collectives and
    sync ops advance nothing — they ride the comm stream), plus the
    completion time of the LAST backward compute op (t_backward_end: the
    horizon collectives can hide behind)."""
    times: List[float] = []
    t = 0.0
    t_bwd_end = 0.0
    for op_ in ops:
        if op_.type not in COMM_OPS:
            t += op_time_s(op_, block, cm)
            if int(op_.attrs.get("op_role", 0)) & 1:
                t_bwd_end = t
        times.append(t)
    return times, (t_bwd_end if t_bwd_end > 0 else t)


def collective_time_s(payload_bytes: float, ring_factor: float, nranks: int,
                      cm: CostModel) -> float:
    """Bidirectional-ring alpha-beta model: launch latency + wire bytes
    over ICI bandwidth.  ``ring_factor`` is 2.0 for allreduce, 1.0 for
    reduce-scatter/all-gather (matches tools/dp_comm_stats._RING_FACTOR)."""
    ring = (nranks - 1) / float(nranks) if nranks > 1 else 0.0
    return cm.launch_s + ring_factor * ring * payload_bytes / cm.ici_bytes_per_s


def model_comm_stream(buckets: Sequence[dict], t_backward_end: float,
                      cm: CostModel) -> dict:
    """Serialize bucket collectives on one comm stream: bucket k starts
    at max(ready_k, finish_{k-1}).  Returns per-bucket (start, finish)
    and the modeled exposed tail — comm time past the backward horizon,
    converted to bytes at ICI rate so it compares against wire bytes.
    Each bucket dict needs ``ready_s`` and ``comm_s`` (and may carry
    anything else through)."""
    t = 0.0
    out = []
    for b in buckets:
        start = max(t, b["ready_s"])
        t = start + b["comm_s"]
        out.append({**b, "start_s": start, "finish_s": t})
    exposed_s = max(0.0, t - t_backward_end)
    return {
        "buckets": out,
        "finish_s": t,
        "t_backward_end_s": t_backward_end,
        "exposed_s": exposed_s,
        "est_exposed_bytes_model": int(exposed_s * cm.ici_bytes_per_s),
    }
