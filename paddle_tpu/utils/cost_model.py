"""Analytic per-op cost model for comm-schedule decisions.

The measurement-driven half of the DP comm layer (reference intent:
PaddlePaddle's adaptive distributed training, arXiv:2112.02752 — cost
models drive the parallelization/communication plan instead of fixed
constants).  Two consumers:

* ``framework/ir.py fuse_all_reduce_pass`` under
  ``FLAGS_fuse_grad_size_in_MB=auto`` partitions the gradient-reduce
  entries into *variable-size* buckets by minimizing the modeled finish
  time of the serialized collective stream against the modeled backward
  timeline (each bucket's collective should finish roughly as the next
  bucket's last gradient becomes ready);
* ``tools/dp_comm_stats.py`` prints the timeline + modeled exposed-comm
  bytes so a schedule change is reviewable without a chip.

The model is deliberately coarse — max(FLOPs/peak, bytes/HBM-bw) per
compute op, a bidirectional-ring alpha-beta model per collective — and
its job is *relative* ordering of schedules, not absolute times.
``CostModel.calibrated`` rescales the compute rates so the modeled
backward matches one profiled step, which is all the bucket decision
needs (the comm/compute ratio).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence, Tuple

#: collectives + sync ops: excluded from the compute timeline (they ride
#: the comm stream the schedule is being built FOR)
COMM_OPS = frozenset({
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "allreduce", "c_fused_allreduce",
    "c_fused_reduce_scatter", "c_reducescatter", "c_allgather",
    "c_broadcast", "broadcast", "c_concat", "c_split", "alltoall",
    "c_sync_comm_stream", "c_sync_calc_stream", "c_wait_comm_stream",
    "c_wait_calc_stream", "barrier", "c_comm_init", "c_comm_init_all",
    "c_gen_nccl_id",
})

#: op type -> which input slots form a (lhs, rhs) GEMM; flops = 2*M*K*N.
#: Grad ops replay two GEMMs (dX and dW), covered by the multiplier.
_MATMUL_OPS: Dict[str, Tuple[str, str, float]] = {
    "mul": ("X", "Y", 1.0),
    "matmul": ("X", "Y", 1.0),
    "matmul_v2": ("X", "Y", 1.0),
    "fc": ("Input", "W", 1.0),
    "mul_grad": ("X", "Y", 2.0),
    "matmul_grad": ("X", "Y", 2.0),
    "matmul_v2_grad": ("X", "Y", 2.0),
    "fused_matmul_bias_act": ("X", "Y", 1.0),
    "fused_matmul_bias_act_grad": ("X", "Y", 2.0),
}


@dataclass(frozen=True)
class CostModel:
    """Device constants for the analytic model.  Defaults approximate a
    single TPU core (the target the schedule ships to — on the CPU proxy
    only the *relative* schedule matters, which these preserve)."""

    flops_per_s: float = 9.0e13       # dense matmul peak
    hbm_bytes_per_s: float = 8.0e11   # memory-bound elementwise ops
    ici_bytes_per_s: float = 4.5e10   # per-chip ring bandwidth
    launch_s: float = 1.0e-6          # per-collective launch/latency
    assumed_batch: int = 64           # stands in for dynamic (-1) dims
    # host link (PCIe-class DMA): the memory_relief_pass prices its
    # memcpy_d2h / memcpy_h2d offload pairs against these; like the ICI
    # constants they are hardware facts and are NOT rescaled by
    # ``calibrated`` (which only retunes the compute rates)
    d2h_bytes_per_s: float = 1.2e10
    h2d_bytes_per_s: float = 1.2e10

    def calibrated(self, measured_backward_s: float,
                   modeled_backward_s: float) -> "CostModel":
        """Rescale compute rates so the modeled backward equals a
        profiled one; comm constants are hardware facts and stay."""
        if measured_backward_s <= 0 or modeled_backward_s <= 0:
            return self
        f = modeled_backward_s / measured_backward_s
        return replace(self, flops_per_s=self.flops_per_s * f,
                       hbm_bytes_per_s=self.hbm_bytes_per_s * f)


# ==========================================================================
# Measured-profile store (r13: the profiler -> autotune calibration loop)
# ==========================================================================
# ``profiler.disable_profiler`` publishes the measured executor step
# time here; ``default_cost_model`` consumes it so every autotune
# decision (framework/ir.py fuse_all_reduce_pass, tools/dp_comm_stats)
# runs on measured rates whenever a profile exists.  The version
# counter participates in the executor / DP compile-cache keys: a new
# profile may move bucket boundaries, so compiled programs keyed on the
# old rates must not be silently reused.
_PROFILE_LOCK = threading.Lock()
_PROFILE: Optional[dict] = None
_CAL_VERSION = 0


def set_measured_profile(step_s: float, per_op_s: Optional[Dict] = None,
                         source: str = ""):
    """Record one profiled step: ``step_s`` is the measured wall time of
    an ``executor_run`` (stands in for the backward horizon — the
    calibration only needs the comm/compute *ratio*), ``per_op_s``
    optionally carries per-event mean times for finer consumers."""
    global _PROFILE, _CAL_VERSION
    if not step_s or step_s <= 0:
        return
    with _PROFILE_LOCK:
        _PROFILE = {"step_s": float(step_s),
                    "per_op_s": dict(per_op_s or {}), "source": source}
        _CAL_VERSION += 1


def measured_profile() -> Optional[dict]:
    with _PROFILE_LOCK:
        return dict(_PROFILE) if _PROFILE is not None else None


def clear_measured_profile():
    global _PROFILE, _CAL_VERSION
    with _PROFILE_LOCK:
        if _PROFILE is not None:
            _PROFILE = None
            _CAL_VERSION += 1


def calibration_version() -> int:
    """Bumped on every profile set/clear — compile caches key on it."""
    with _PROFILE_LOCK:
        return _CAL_VERSION


def default_cost_model(ops: Optional[Sequence] = None,
                       block=None) -> "CostModel":
    """The cost model every schedule decision should start from: the
    hand-set defaults, rescaled against the measured profile when one
    exists (and a program is given to model against).  Without a
    profile this is exactly ``CostModel()`` — the pre-r13 behavior."""
    cm = CostModel()
    prof = measured_profile()
    if prof and ops is not None and block is not None:
        _, modeled = backward_timeline(ops, block, cm)
        cm = cm.calibrated(prof["step_s"], modeled)
    return cm


def _dims(block, name, assumed_batch) -> Optional[List[int]]:
    var = block._find_var_recursive(name)
    if var is None or var.shape is None:
        return None
    out = []
    for d in var.shape:
        if d is None:
            return None
        d = int(d)
        out.append(assumed_batch if d < 0 else d)
    return out


def _numel(dims) -> int:
    n = 1
    for d in dims:
        n *= max(d, 1)
    return n


#: Epilogue-shaped ops whose real HBM traffic the generic "touched
#: bytes" default mis-states (r14 fix): the default sums EVERY declared
#: input/output, but batch_norm's small per-channel vectors are noise
#: while its big tensor is re-read for the normalize pass after the
#: stats pass, and grad ops re-read the forward tensors for the
#: reduction pass before the dx pass.  Each entry: (main-tensor slot,
#: passes over that tensor, flops per element).  One "pass" = one full
#: HBM read or write of the main tensor; these are exactly the numbers
#: ``rank_fusion_candidates`` compares, so mis-stating them mis-ranks
#: the conv+BN+act chains the fusion layer targets.
_EPILOGUE_TRAFFIC: Dict[str, Tuple[str, float, float]] = {
    # train BN: stats read + normalize read + y write
    "batch_norm": ("X", 3.0, 8.0),
    # reductions read (x, dy) + dx pass reads (x, dy) + dx write
    "batch_norm_grad": ("X", 5.0, 12.0),
    "fused_batch_norm_act": ("X", 3.0, 9.0),
    "fused_batch_norm_act_grad": ("X", 5.0, 13.0),
    # + z read / dz write
    "fused_bn_add_activation": ("X", 4.0, 10.0),
    "fused_bn_add_activation_grad": ("X", 6.0, 13.0),
    # activation grads: read (out, dout), write dx — the declared X
    # input is never touched by the jnp lowering's vjp
    "relu_grad": ("Out", 3.0, 1.0),
    "leaky_relu_grad": ("Out", 3.0, 1.0),
    "sigmoid_grad": ("Out", 3.0, 2.0),
    "tanh_grad": ("Out", 3.0, 2.0),
    "gelu_grad": ("Out", 3.0, 6.0),
    "elu_grad": ("Out", 3.0, 2.0),
    # read dout, write (dx, dy) — Out/X/Y are pass-through declarations
    "elementwise_add_grad": ("Out", 3.0, 1.0),
}


def _main_dims(op_, block, slot, assumed_batch):
    names = op_.inputs.get(slot) or op_.outputs.get(slot) or [None]
    return _dims(block, names[0], assumed_batch) if names[0] else None


def op_flops_bytes(op_, block, assumed_batch=64) -> Tuple[float, float]:
    """(flops, moved bytes) for one compute op.  GEMM-shaped ops get
    2*M*K*N flops; conv2d gets 2*out_elems*receptive-field; epilogue
    ops (BN, activation grads) get the pass-accurate table above;
    everything else is elementwise over its touched bytes (4 B/elem
    assumed — the model cares about ratios, not dtypes)."""
    ep = _EPILOGUE_TRAFFIC.get(op_.type)
    if ep is not None:
        slot, passes, flops_per_elem = ep
        dims = _main_dims(op_, block, slot, assumed_batch)
        if dims:
            if (op_.type.startswith(("batch_norm", "fused_batch_norm",
                                     "fused_bn_add"))
                    and (op_.attrs.get("is_test")
                         or op_.attrs.get("use_global_stats"))):
                passes -= 1.0  # frozen stats: no stats pass (any BN kind)
            numel = _numel(dims)
            return flops_per_elem * numel, passes * numel * 4.0
    touched = 0
    for names in list(op_.inputs.values()) + list(op_.outputs.values()):
        for n in names:
            if n == "@EMPTY@":
                continue
            dims = _dims(block, n, assumed_batch)
            if dims:
                touched += _numel(dims) * 4
    mm = _MATMUL_OPS.get(op_.type)
    if mm is not None:
        lhs_slot, rhs_slot, mult = mm
        lhs = op_.inputs.get(lhs_slot, [None])[0]
        rhs = op_.inputs.get(rhs_slot, [None])[0]
        ld = _dims(block, lhs, assumed_batch) if lhs else None
        rd = _dims(block, rhs, assumed_batch) if rhs else None
        if ld and rd and len(rd) >= 2:
            m = _numel(ld[:-1])
            k = ld[-1]
            n = rd[-1]
            return 2.0 * m * k * n * mult, float(touched)
    if op_.type in ("conv2d", "depthwise_conv2d", "conv2d_grad",
                    "depthwise_conv2d_grad", "fused_conv_bn_act",
                    "fused_conv_bn_act_grad"):
        out_slot = "Output" if ("Output" in op_.outputs
                                or "Output" in op_.inputs) else "Out"
        out = op_.outputs.get(out_slot, [None])[0] or \
            op_.inputs.get(out_slot, [None])[0]
        fil = op_.inputs.get("Filter", [None])[0]
        od = _dims(block, out, assumed_batch) if out else None
        fd = _dims(block, fil, assumed_batch) if fil else None
        if od and fd and len(fd) == 4:
            mult = 2.0 if op_.type.endswith("_grad") else 1.0
            return (2.0 * _numel(od) * fd[1] * fd[2] * fd[3] * mult,
                    float(touched))
    return float(_numel([1])), float(touched)


def op_time_s(op_, block, cm: CostModel) -> float:
    flops, nbytes = op_flops_bytes(op_, block, cm.assumed_batch)
    return max(flops / cm.flops_per_s, nbytes / cm.hbm_bytes_per_s)


def backward_timeline(ops: Sequence, block, cm: CostModel
                      ) -> Tuple[List[float], float]:
    """Cumulative modeled completion time per op index (collectives and
    sync ops advance nothing — they ride the comm stream), plus the
    completion time of the LAST backward compute op (t_backward_end: the
    horizon collectives can hide behind)."""
    times: List[float] = []
    t = 0.0
    t_bwd_end = 0.0
    for op_ in ops:
        if op_.type not in COMM_OPS:
            t += op_time_s(op_, block, cm)
            if int(op_.attrs.get("op_role", 0)) & 1:
                t_bwd_end = t
        times.append(t)
    return times, (t_bwd_end if t_bwd_end > 0 else t)


def collective_time_s(payload_bytes: float, ring_factor: float, nranks: int,
                      cm: CostModel) -> float:
    """Bidirectional-ring alpha-beta model: launch latency + wire bytes
    over ICI bandwidth.  ``ring_factor`` is 2.0 for allreduce, 1.0 for
    reduce-scatter/all-gather (matches tools/dp_comm_stats._RING_FACTOR)."""
    ring = (nranks - 1) / float(nranks) if nranks > 1 else 0.0
    return cm.launch_s + ring_factor * ring * payload_bytes / cm.ici_bytes_per_s


# ==========================================================================
# Profile-ranked epilogue-fusion candidates (r14)
# ==========================================================================
# ``find_fusion_chains`` is the structural half: walk a block for the
# chains the Pallas fusion layer can rewrite — conv2d -> batch_norm /
# fused_batch_norm_act / fused_bn_add_activation (with the matching grad
# pair), and mul/matmul -> elementwise_add(1-D bias) -> activation (with
# its grad triple).  ``rank_fusion_candidates`` is the measurement half:
# score each chain by modeled memory-traffic savings at the cost model's
# (profile-calibrated, see default_cost_model) HBM rate, preferring
# measured per-op self-times when the profile carries them.  The
# framework/ir.py fuse_epilogue_pass consumes the ranking; the finder
# lives HERE so the ranking and the rewrite can never disagree about
# what a fusible chain is.

#: bn-shaped ops a conv epilogue can absorb.  Plain ``batch_norm`` is
#: matched only with a trailing ``relu`` (the raw conv->BN->ReLU triple,
#: for programs the fuse_bn_act passes haven't visited): a ReLU-less BN
#: keeps its generic-vjp backward under FLAGS_tpu_fuse=0, and rewriting
#: it onto the closed-form fused backward would break the flag's
#: bit-for-bit contract.
_BN_OPS = ("batch_norm", "fused_batch_norm_act", "fused_bn_add_activation")
#: activations the fused matmul epilogue supports
FUSABLE_ACTS = ("relu", "sigmoid", "tanh", "gelu")


def _consumer_map(ops) -> Dict[str, List]:
    cons: Dict[str, List] = {}
    for op_ in ops:
        for names in op_.inputs.values():
            for n in names:
                cons.setdefault(n, []).append(op_)
    return cons


def _only(users, allowed) -> bool:
    allowed_ids = {id(a) for a in allowed if a is not None}
    return all(id(u) in allowed_ids for u in users)


def _first(users, pred):
    return next((u for u in users if pred(u)), None)


def _conv_chain(conv, cons, block):
    y0 = conv.outputs.get("Output", [None])[0]
    if not y0 or y0 == "@EMPTY@":
        return None
    users = cons.get(y0, [])
    bn = _first(users, lambda o: o.type in _BN_OPS
                and o.inputs.get("X", [None])[0] == y0)
    if bn is None:
        return None
    cf = conv.attrs.get("data_format", "NCHW")
    if bn.attrs.get("data_layout", "NCHW") != cf:
        return None  # mixed-layout chain: the fused op has ONE layout attr
    if bn.type != "batch_norm" and \
            bn.attrs.get("act_type", "relu") != "relu":
        return None
    bn_grad = _first(users, lambda o: o.type == bn.type + "_grad"
                     and o.inputs.get("X", [None])[0] == y0)
    conv_grad = _first(users, lambda o: o.type == conv.type + "_grad"
                       and o.inputs.get("Output", [None])[0] == y0)
    if not _only(users, (bn, bn_grad, conv_grad)):
        return None
    if (bn_grad is None) != (conv_grad is None):
        return None  # half a backward: leave it alone
    bn_y = bn.outputs.get("Y", [None])[0]
    act_op = act_grad = None
    out = bn_y
    if bn.type == "batch_norm":
        # the raw triple: BN must feed a relu (fusing a ReLU-less plain
        # BN would swap its generic-vjp backward for the closed form)
        b_users = cons.get(bn_y, [])
        act_op = _first(b_users, lambda o: o.type == "relu"
                        and o.inputs.get("X", [None])[0] == bn_y)
        if act_op is None:
            return None
        act_grad = _first(b_users, lambda o: o.type == "relu_grad"
                          and o.inputs.get("X", [None])[0] == bn_y)
        if not _only(b_users, (act_op, act_grad, bn_grad)):
            return None
        if (act_grad is None) != (bn_grad is None):
            return None
        out = act_op.outputs["Out"][0]
        if bn_grad is not None:
            dy1 = act_grad.outputs.get("X@GRAD", [None])[0]
            if (not dy1 or bn_grad.inputs.get("Y@GRAD", [None])[0] != dy1
                    or not _only(cons.get(dy1, []), (bn_grad,))
                    or act_grad.inputs.get("Out", [None])[0] != out):
                return None
    if bn_grad is not None:
        # the BN backward's dX must feed exactly conv_grad's Output@GRAD
        dy0 = bn_grad.outputs.get("X@GRAD", [None])[0]
        if (not dy0 or dy0 == "@EMPTY@"
                or conv_grad.inputs.get("Output@GRAD", [None])[0] != dy0
                or not _only(cons.get(dy0, []), (conv_grad,))):
            return None
        if bn.type != "batch_norm" and \
                bn_grad.inputs.get("Y", [None])[0] != out:
            return None
    z = bn.inputs.get("Z", [None])[0] if bn.type == "fused_bn_add_activation" \
        else None
    return {
        "kind": "conv_bn_act", "conv": conv, "bn": bn,
        "conv_grad": conv_grad, "bn_grad": bn_grad,
        "act_op": act_op, "act_grad": act_grad,
        "act": "relu", "z": z, "conv_out": y0,
        "bn_y": bn_y if act_op is not None else None, "out": out,
        "dconv": (bn_grad.outputs["X@GRAD"][0] if bn_grad is not None
                  else None),
    }


def _matmul_ok(op_, block):
    if op_.type == "mul":
        return int(op_.attrs.get("y_num_col_dims", 1)) == 1
    if op_.type in ("matmul", "matmul_v2"):
        if op_.attrs.get("transpose_X") or op_.attrs.get("transpose_Y") or \
                op_.attrs.get("trans_x") or op_.attrs.get("trans_y"):
            return False
        if float(op_.attrs.get("alpha", 1.0) or 1.0) != 1.0:
            return False
        xv = block._find_var_recursive(op_.inputs.get("X", [None])[0] or "")
        return xv is not None and xv.shape is not None and len(xv.shape) == 2
    return False


def _matmul_chain(mm, cons, block):
    if not _matmul_ok(mm, block):
        return None
    y0 = mm.outputs.get("Out", [None])[0]
    wv = block._find_var_recursive(mm.inputs.get("Y", [None])[0] or "")
    if not y0 or wv is None or wv.shape is None or len(wv.shape) != 2:
        return None
    users = cons.get(y0, [])
    xnc = int(mm.attrs.get("x_num_col_dims", 1))

    def _bias_add(o):
        if o.type != "elementwise_add" or o.inputs.get("X", [None])[0] != y0:
            return False
        bvar = block._find_var_recursive(o.inputs.get("Y", [None])[0] or "")
        if bvar is None or bvar.shape is None or len(bvar.shape) != 1:
            return False
        return int(o.attrs.get("axis", -1)) in (-1, xnc)

    add = _first(users, _bias_add)
    if add is None:
        return None
    mm_grad = _first(users, lambda o: o.type == mm.type + "_grad")
    add_grad = _first(users, lambda o: o.type == "elementwise_add_grad"
                      and o.inputs.get("X", [None])[0] == y0)
    if not _only(users, (add, add_grad, mm_grad)):
        return None
    ya = add.outputs["Out"][0]
    a_users = cons.get(ya, [])
    act_op = _first(a_users, lambda o: o.type in FUSABLE_ACTS
                    and o.inputs.get("X", [None])[0] == ya)
    if act_op is None:
        return None
    if act_op.type == "gelu" and act_op.attrs.get("approximate"):
        return None  # kernel/fallback implement the exact erf form only
    act_grad = _first(a_users, lambda o: o.type == act_op.type + "_grad"
                      and o.inputs.get("X", [None])[0] == ya)
    if not _only(a_users, (act_op, act_grad, add_grad)):
        return None
    grads = (act_grad, add_grad, mm_grad)
    if any(g is None for g in grads) != all(g is None for g in grads):
        return None  # partial backward
    y1 = act_op.outputs["Out"][0]
    if act_grad is not None:
        dya = act_grad.outputs.get("X@GRAD", [None])[0]
        if (not dya or add_grad.inputs.get("Out@GRAD", [None])[0] != dya
                or not _only(cons.get(dya, []), (add_grad,))):
            return None
        dy0 = add_grad.outputs.get("X@GRAD", [None])[0]
        if (not dy0 or mm_grad.inputs.get("Out@GRAD", [None])[0] != dy0
                or not _only(cons.get(dy0, []), (mm_grad,))):
            return None
        if act_grad.inputs.get("Out", [None])[0] != y1:
            return None
    return {
        "kind": "matmul_bias_act", "mm": mm, "add": add, "act_op": act_op,
        "mm_grad": mm_grad, "add_grad": add_grad, "act_grad": act_grad,
        "act": act_op.type, "mm_out": y0, "add_out": ya, "out": y1,
        "xnc": xnc,
    }


def find_fusion_chains(block) -> List[dict]:
    """Structural matches for every epilogue-fusable chain in ``block``
    (fwd + the matching grad chain, or fwd-only in inference programs).
    Safety here covers dataflow exclusivity; the IR pass adds the
    protected/fetch and cross-block checks before rewriting."""
    cons = _consumer_map(block.ops)
    chains = []
    for op_ in block.ops:
        if op_.type in ("conv2d", "depthwise_conv2d"):
            ch = _conv_chain(op_, cons, block)
        elif op_.type in ("mul", "matmul", "matmul_v2"):
            ch = _matmul_chain(op_, cons, block)
        else:
            ch = None
        if ch is not None:
            chains.append(ch)
    return chains


def chain_saved_traffic(chain, block, assumed_batch=64) -> dict:
    """Modeled HBM bytes the fused rewrite stops moving, per
    intermediate.  One saved "pass" = one full read or write of that
    tensor at 4 B/elem.  conv chains: the conv output's separate
    normalize-pass re-read folds into the single fused epilogue pass
    (2 passes when frozen stats let the whole tensor die), and the grad
    chain's dX-of-BN intermediate becomes kernel-internal (write+read).
    matmul chains: the matmul output and the pre-act bias sum (and
    their grad cotangents) all become tile-internal."""

    def nbytes(name):
        dims = _dims(block, name, assumed_batch)
        return _numel(dims) * 4 if dims else 0

    saved = {}
    if chain["kind"] == "conv_bn_act":
        frozen = bool(chain["bn"].attrs.get("is_test")
                      or chain["bn"].attrs.get("use_global_stats"))
        saved[chain["conv_out"]] = nbytes(chain["conv_out"]) * \
            (2.0 if frozen else 1.0)
        if chain.get("bn_y"):  # raw triple: the pre-relu BN output dies
            saved[chain["bn_y"]] = nbytes(chain["bn_y"]) * 2.0
            if chain["act_grad"] is not None:
                saved[chain["bn_y"] + "@GRAD"] = nbytes(chain["bn_y"]) * 2.0
        if chain["bn_grad"] is not None:
            saved[chain["dconv"]] = nbytes(chain["dconv"]) * 2.0
    else:
        saved[chain["mm_out"]] = nbytes(chain["mm_out"]) * 2.0
        saved[chain["add_out"]] = nbytes(chain["add_out"]) * 2.0
        if chain["act_grad"] is not None:
            saved[chain["add_out"] + "@GRAD"] = nbytes(chain["add_out"]) * 2.0
            saved[chain["mm_out"] + "@GRAD"] = nbytes(chain["mm_out"]) * 2.0
    return {"per_tensor": saved,
            "total_bytes": float(sum(saved.values()))}


def rank_fusion_candidates(program, profile=None,
                           cm: Optional[CostModel] = None) -> List[dict]:
    """Rank every fusible chain in ``program`` by modeled+measured
    memory-traffic savings, best first.

    ``profile``: a measured-profile dict (``measured_profile()`` shape);
    defaults to the store the profiler feeds.  With a profile the cost
    model is rescaled against the measured step (``default_cost_model``)
    and, when ``per_op_s`` carries mean self-times for the chain's
    epilogue op types, the measured time wins over the modeled one.
    Returns dicts: kind / op types / saved_bytes / est_saved_s /
    measured_epilogue_s / score_s / calibrated, plus the raw ``chain``
    match for the IR pass."""
    block = program.global_block()
    ops = list(block.ops)
    if profile is None:
        profile = measured_profile()
    if cm is None:
        cm = CostModel()
        if profile:
            _, modeled = backward_timeline(ops, block, cm)
            cm = cm.calibrated(profile["step_s"], modeled)
    per_op = dict((profile or {}).get("per_op_s") or {})
    # Measured per-op self-times are means PER OP TYPE (the profiler's
    # event aggregation) — apportion each type's measured time across
    # the chains touching it by their share of that type's modeled
    # bytes, so same-typed chains of different sizes still rank by
    # size instead of collapsing into a tie.
    raw = []
    type_bytes_total: Dict[str, float] = {}
    for chain in find_fusion_chains(block):
        if chain["kind"] == "conv_bn_act":
            ep_ops = [chain["bn"], chain["act_op"], chain["bn_grad"],
                      chain["act_grad"]]
        else:
            ep_ops = [chain["add"], chain["act_op"], chain["add_grad"],
                      chain["act_grad"]]
        ep_ops = [o for o in ep_ops if o is not None]
        ep_bytes = {}
        for o in ep_ops:
            _, nbytes = op_flops_bytes(o, block, cm.assumed_batch)
            ep_bytes[o.type] = ep_bytes.get(o.type, 0.0) + nbytes
            type_bytes_total[o.type] = \
                type_bytes_total.get(o.type, 0.0) + nbytes
        raw.append((chain, ep_ops, ep_bytes))
    out = []
    for chain, ep_ops, ep_bytes in raw:
        traffic = chain_saved_traffic(chain, block, cm.assumed_batch)
        est_s = traffic["total_bytes"] / cm.hbm_bytes_per_s
        types = [o.type for o in ep_ops]
        measured = sum(
            per_op[t] * (b / type_bytes_total[t])
            for t, b in ep_bytes.items()
            if t in per_op and type_bytes_total[t] > 0)
        out.append({
            "kind": chain["kind"],
            "ops": [chain["conv"].type if chain["kind"] == "conv_bn_act"
                    else chain["mm"].type] + types,
            "out": chain["out"],
            "saved_bytes": int(traffic["total_bytes"]),
            "per_tensor": traffic["per_tensor"],
            "est_saved_s": est_s,
            "measured_epilogue_s": measured or None,
            "score_s": measured if measured else est_s,
            "calibrated": bool(profile),
            "chain": chain,
        })
    out.sort(key=lambda r: -r["score_s"])
    return out


def model_comm_stream(buckets: Sequence[dict], t_backward_end: float,
                      cm: CostModel) -> dict:
    """Serialize bucket collectives on one comm stream: bucket k starts
    at max(ready_k, finish_{k-1}).  Returns per-bucket (start, finish)
    and the modeled exposed tail — comm time past the backward horizon,
    converted to bytes at ICI rate so it compares against wire bytes.
    Each bucket dict needs ``ready_s`` and ``comm_s`` (and may carry
    anything else through)."""
    t = 0.0
    out = []
    for b in buckets:
        start = max(t, b["ready_s"])
        t = start + b["comm_s"]
        out.append({**b, "start_s": start, "finish_s": t})
    exposed_s = max(0.0, t - t_backward_end)
    return {
        "buckets": out,
        "finish_s": t,
        "t_backward_end_s": t_backward_end,
        "exposed_s": exposed_s,
        "est_exposed_bytes_model": int(exposed_s * cm.ici_bytes_per_s),
    }
