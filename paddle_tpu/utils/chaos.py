"""Deterministic fault injection (``FLAGS_chaos``).

The fault-tolerance layer's oracle needs failures that are *exactly*
reproducible: the same schedule string must kill the same step, drop the
same RPCs and truncate the same checkpoint file on every run, so a
train -> inject -> resume experiment (tools/chaos_train.py) can assert
loss-trajectory parity instead of "it usually recovers".

Schedule grammar — ``;``-separated events, all optional::

    seed=N                 RNG seed for probabilistic events (default 0)
    kill@K                 kill the process at the start of step K
                           (os._exit(137)); ``kill@K:raise`` raises
                           ChaosKilled instead (in-process tests)
    rpc_drop=PHASE@N       drop exactly the Nth RPC (1-based, counted
                           across the process) at PHASE: ``send`` =
                           before the request leaves (server never sees
                           it), ``recv`` = after it was sent but before
                           the reply is read (server applied it; the
                           reply is lost) — the double-apply trap
    rpc_drop=PHASE:P       drop each RPC at PHASE with probability P
    rpc_delay=MS:P         sleep MS milliseconds before an RPC with
                           probability P
    trunc_ckpt@N           after the Nth checkpoint save completes,
                           truncate one of its data files in half
                           (seeded choice) — load must reject it
    nan_inject=NAME@K      at the start of step K (on_step), arm a NaN
                           poison on the op NAME names — an op TYPE
                           (every instance) or an output VAR name.  The
                           poisoned op's float outputs become NaN for
                           that one step (the executor/DP compile caches
                           key on the armed target, so step K traces a
                           poisoned variant and step K+1 falls back to
                           the clean cached compile) — the end-to-end
                           numerics oracle: the FLAGS_check_nan_inf
                           sentinel names the op, the NaN/Inf flight
                           recorder dumps debris, and
                           tools/bisect_divergence.py localizes to it

Serving faults (r18 — hooked into the ServingEngine step loop and the
overload loadgen, tools/overload_bench.py):

    decode_delay=MS@N      sleep MS ms before the Nth batched decode
                           step (1-based, counted per process run)
    decode_delay=MS:P      sleep MS ms before each decode step with
                           probability P (seeded)
    req_burst=N@K          at serving step K, queue N extra synthetic
                           requests for the loadgen to inject
                           (``take_burst`` — the engine cannot fabricate
                           requests itself)
    pool_spike=P@K:D       at serving step K, seize up to P KV-pool
                           pages for D steps (default 4) — admission
                           backpressure + preemption pressure on
                           demand.  Refcount-correct under CoW prefix
                           caching: only refcount-0 pages are seized
                           (a live shared prefix is never invalidated)
                           and release decrements through the normal
                           free path

Example: ``FLAGS_chaos="seed=7;kill@12;rpc_drop=recv@3"``.

Hooks are called from the PS client (``on_rpc``), the checkpoint writer
(``on_checkpoint_saved``), the training loop (``on_step``) and the
serving engine (``on_serving_step`` / ``on_decode_step``).  With
``FLAGS_chaos`` unset every hook is a no-op behind one cached ``None``
check, so production paths pay nothing.
"""
from __future__ import annotations

import os
import random
import re
import threading
import time
import weakref
from typing import Optional

from . import flags


class ChaosKilled(RuntimeError):
    """Raised by kill@K:raise — the in-process stand-in for SIGKILL."""


class ChaosRPCDrop(ConnectionError):
    """Injected transport failure — a ConnectionError so the client's
    retry/eviction path treats it exactly like a real dead socket."""


_EVENT_RE = re.compile(r"^(?P<key>[a-z_]+)(?:[=@](?P<val>.*))?$")

#: fault kinds that only mean anything inside a serving engine step
#: loop — training tools (tools/chaos_train.py) must REJECT them with a
#: clear parse error instead of silently arming a no-op schedule
SERVING_FAULT_KEYS = frozenset({"decode_delay", "req_burst", "pool_spike"})


class FaultSchedule:
    """Parsed FLAGS_chaos schedule.  All state (RPC counter, checkpoint
    counter, RNG) lives here so determinism is per-process-run."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        self.kill_step: Optional[int] = None
        self.kill_mode = "exit"            # "exit" | "raise"
        self.drop_at = {}                  # phase -> set of 1-based indices
        self.drop_p = {}                   # phase -> probability
        self.delay_ms = 0.0
        self.delay_p = 0.0
        self.trunc_ckpts: set = set()      # 1-based save indices to truncate
        self.nan_at = {}                   # step -> op type / out-var name
        # serving faults (r18)
        self.decode_delay_ms = 0.0
        self.decode_delay_p = 0.0
        self.decode_delay_at = {}          # 1-based decode step -> ms
        self.burst_at = {}                 # serving step -> extra requests
        self.spike_at = {}                 # serving step -> (pages, steps)
        self._rpc_n = 0
        self._ckpt_n = 0
        self._decode_n = 0
        self._burst_pending = 0
        self._spike_live = []              # [(release_step, kv weakref, sid)]
        self._lock = threading.Lock()
        self._parse(spec)
        self._rng = random.Random(self.seed)
        _set_nan_poison(None)  # a fresh schedule starts disarmed

    # ------------------------------------------------------------------
    def _parse(self, spec: str):
        for raw in spec.split(";"):
            item = raw.strip()
            if not item:
                continue
            key, _, val = item.partition("=") if "=" in item else \
                item.partition("@")
            key, val = key.strip(), val.strip()
            if key == "seed":
                self.seed = int(val)
            elif key == "kill":
                step, _, mode = val.partition(":")
                self.kill_step = int(step)
                if mode:
                    if mode not in ("exit", "raise"):
                        raise ValueError(f"FLAGS_chaos: bad kill mode {mode!r}")
                    self.kill_mode = mode
            elif key == "rpc_drop":
                if "@" in val:
                    phase, _, n = val.partition("@")
                    self._phase_ok(phase)
                    self.drop_at.setdefault(phase, set()).add(int(n))
                else:
                    phase, _, p = val.partition(":")
                    self._phase_ok(phase)
                    self.drop_p[phase] = float(p)
            elif key == "rpc_delay":
                ms, _, p = val.partition(":")
                self.delay_ms = float(ms.rstrip("ms") or 0)
                self.delay_p = float(p or 1.0)
            elif key == "trunc_ckpt":
                self.trunc_ckpts.add(int(val))
            elif key == "nan_inject":
                name, _, at = val.partition("@")
                name = name.strip()
                if not name or not at:
                    raise ValueError(
                        f"FLAGS_chaos: nan_inject needs OP@STEP, "
                        f"got {item!r}")
                self.nan_at[int(at)] = name
            elif key == "decode_delay":
                try:
                    if "@" in val:
                        ms, _, n = val.partition("@")
                        self.decode_delay_at[int(n)] = \
                            self._ms(ms, item)
                    else:
                        ms, _, p = val.partition(":")
                        self.decode_delay_ms = self._ms(ms, item)
                        self.decode_delay_p = float(p or 1.0)
                except ValueError as e:
                    raise ValueError(
                        f"FLAGS_chaos: decode_delay needs MS@N or "
                        f"MS[:P], got {item!r}") from e
            elif key == "req_burst":
                n, _, at = val.partition("@")
                if not at:
                    raise ValueError(
                        f"FLAGS_chaos: req_burst needs N@STEP, got {item!r}")
                self.burst_at[int(at)] = self.burst_at.get(int(at), 0) \
                    + int(n)
            elif key == "pool_spike":
                pages, _, at = val.partition("@")
                if not at:
                    raise ValueError(
                        f"FLAGS_chaos: pool_spike needs PAGES@STEP[:STEPS], "
                        f"got {item!r}")
                step, _, dur = at.partition(":")
                self.spike_at[int(step)] = (int(pages), int(dur or 4))
            else:
                raise ValueError(f"FLAGS_chaos: unknown event {item!r}")

    @staticmethod
    def _ms(ms: str, item: str) -> float:
        """Strict milliseconds value: an empty or non-numeric MS must
        be a parse error, never a silently-armed 0 ms no-op (the same
        never-silently-ignored contract chaos_train enforces)."""
        ms = ms.strip().rstrip("ms").strip()
        if not ms:
            raise ValueError(f"FLAGS_chaos: missing MS value in {item!r}")
        return float(ms)

    @staticmethod
    def _phase_ok(phase: str):
        if phase not in ("send", "recv"):
            raise ValueError(f"FLAGS_chaos: rpc phase must be send|recv, "
                             f"got {phase!r}")

    # -- hooks ---------------------------------------------------------
    def on_step(self, step: int):
        """Training-loop hook: arm/disarm the NaN poison for this step
        and kill the rank at the scheduled step."""
        tgt = self.nan_at.get(step)
        _set_nan_poison(tgt)
        if tgt is not None:
            self._mark("nan_inject", "step", step, tgt)
        if self.kill_step is None or step != self.kill_step:
            return
        if self.kill_mode == "raise":
            self._mark("kill", "step", step, "")
            raise ChaosKilled(f"chaos: killed at step {step}")
        os._exit(137)  # SIGKILL-faithful: no atexit, no flush

    def on_rpc(self, phase: str, op: str = ""):
        """PS-client hook, called once per (attempted) RPC per phase.
        The call index is shared across phases (one RPC = one index) so
        ``send@N`` and ``recv@N`` name the same call."""
        with self._lock:
            if phase == "send":
                self._rpc_n += 1
            n = self._rpc_n
            delay = (self.delay_ms > 0 and phase == "send"
                     and self._rng.random() < self.delay_p)
            drop = (n in self.drop_at.get(phase, ())
                    or (phase in self.drop_p
                        and self._rng.random() < self.drop_p[phase]))
        if delay:
            self._mark("rpc_delay", phase, n, op)
            time.sleep(self.delay_ms / 1e3)
        if drop:
            self._mark("rpc_drop", phase, n, op)
            raise ChaosRPCDrop(
                f"chaos: dropped rpc #{n} ({op or '?'}) at {phase}")

    def serving_faults(self) -> set:
        """Armed serving-only fault kinds (SERVING_FAULT_KEYS subset) —
        training tools reject schedules where this is non-empty."""
        out = set()
        if self.decode_delay_at or self.decode_delay_ms > 0:
            out.add("decode_delay")
        if self.burst_at:
            out.add("req_burst")
        if self.spike_at:
            out.add("pool_spike")
        return out

    def on_decode_step(self):
        """Serving-engine hook, called once per batched decode step:
        sleep before the Nth (or each, with probability P) decode."""
        with self._lock:
            self._decode_n += 1
            n = self._decode_n
            ms = self.decode_delay_at.get(n, 0.0)
            if (not ms and self.decode_delay_ms > 0
                    and self._rng.random() < self.decode_delay_p):
                ms = self.decode_delay_ms
        if ms:
            self._mark("decode_delay", "decode", n, f"{ms}ms")
            time.sleep(ms / 1e3)

    def on_serving_step(self, engine, step: int):
        """Engine-step hook (``step`` is the engine's own 1-based step
        counter): apply/release pool-pressure spikes and queue request
        bursts for the loadgen (``take_burst``).  Deterministic: both
        are keyed on the step index, never on wall time."""
        burst = self.burst_at.get(step, 0)
        if burst:
            with self._lock:
                self._burst_pending += burst
            self._mark("req_burst", "serving", step, f"{burst}req")
        kv = getattr(engine, "kv", None)
        if kv is None:
            return
        with self._lock:
            # release entries for THIS engine's pool only (two engines
            # may share one process-wide schedule with independent step
            # counters); dead engines' entries are pruned, never freed
            # against the wrong pool
            release, keep = [], []
            for rel, kvref, sid in self._spike_live:
                target = kvref()
                if target is None:
                    continue                      # engine gone, prune
                if target is kv and rel <= step:
                    release.append(sid)
                else:
                    keep.append((rel, kvref, sid))
            self._spike_live = keep
            spike = self.spike_at.get(step)
        for sid in release:
            kv.free_sequence(sid)
        if spike:
            pages, dur = spike
            sid = f"__chaos_spike_{step}__"
            got = 0
            for _ in range(pages):
                # one full page per append; stop at pool exhaustion —
                # a spike SQUEEZES the pool, it never deadlocks it.
                # With CoW prefix caching (r19) the seizure stays
                # refcount-correct by construction: append_tokens only
                # hands out refcount-0 pages (free, or cached entries
                # through the seeded eviction order), NEVER a page a
                # live sequence maps — a spike can evict a cold cached
                # prefix but can't invalidate a live shared one — and
                # tokens=None marks the spike sequence OPAQUE so its
                # garbage pages are never indexed as cache content.
                # The release below decrements refcounts through the
                # same free_sequence path every sequence uses.
                if kv.append_tokens(sid, self.page_size_of(engine),
                                    tokens=None) is None:
                    break
                got += 1
            if got:
                with self._lock:
                    self._spike_live.append(
                        (step + dur, weakref.ref(kv), sid))
                self._mark("pool_spike", "serving", step, f"{got}pg")

    @staticmethod
    def page_size_of(engine) -> int:
        core = getattr(engine, "core", None)
        return core.kv_config.page_size if core is not None else 1

    def take_burst(self) -> int:
        """Pop the pending burst count (loadgen side of req_burst)."""
        with self._lock:
            n = self._burst_pending
            self._burst_pending = 0
            return n

    def _mark(self, kind: str, phase: str, n: int, op: str):
        """Injected fault -> telemetry counter + chaos timeline lane
        (merged into the unified chrome trace when profiling) + an
        annotation on the CURRENT request/RPC span (r17): the trace of
        a chaos run shows WHY a span stalled — the event carries the
        chaos kind and the schedule seed, correlating the aggregate
        ``chaos_injections_total`` count to the affected request."""
        from . import telemetry as tm

        tm.counter("chaos_injections_total",
                   "faults injected by the FLAGS_chaos schedule",
                   labels=("kind",)).labels(kind=kind).inc()
        from . import tracing

        tracing.annotate(f"chaos:{kind}",
                         {"phase": phase, "n": n, "op": op or "?",
                          "seed": self.seed})
        from .. import profiler

        profiler.instant_event(
            f"chaos:{kind}", cat="chaos",
            args={"phase": phase, "n": n, "op": op or "?"})

    def on_checkpoint_saved(self, dirname: str):
        """Checkpoint-writer hook: after the Nth completed save,
        truncate one data file (never the manifest — the point is that
        checksums catch a torn payload, not a missing commit record)."""
        with self._lock:
            self._ckpt_n += 1
            n = self._ckpt_n
        if n not in self.trunc_ckpts:
            return
        files = sorted(f for f in os.listdir(dirname)
                       if f != "manifest.json"
                       and os.path.isfile(os.path.join(dirname, f)))
        if not files:
            return
        victim = os.path.join(
            dirname, files[random.Random(self.seed + n).randrange(len(files))])
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.truncate(size // 2)
        return victim

    def rpc_calls(self) -> int:
        with self._lock:
            return self._rpc_n


#: the armed NaN-poison target (nan_inject=NAME@K, set for the duration
#: of step K by on_step).  A bare module global read on the op-dispatch
#: path (ops/registry.py run_op) — one None check when chaos is off.
_NAN_POISON: Optional[str] = None


def _set_nan_poison(target: Optional[str]):
    global _NAN_POISON
    _NAN_POISON = target


def nan_poison_target() -> Optional[str]:
    """The op type / output var the current step must poison with NaN,
    or None.  Consumed by ops/registry.run_op and the compile cache
    keys (executor / DP) so the poisoned trace is never reused."""
    return _NAN_POISON


def consume_nan_poison():
    """Disarm after the dispatch that ran under the armed target — the
    executor / DP step paths call this when their run completes (or
    raises), so a poison armed at the FINAL step of a loop can never
    leak into an unrelated later compile in the same process (the next
    ``on_step`` call is not guaranteed to exist)."""
    _set_nan_poison(None)


_cached: Optional[FaultSchedule] = None
_cached_spec: Optional[str] = None
_cache_lock = threading.Lock()


def schedule() -> Optional[FaultSchedule]:
    """The process's active schedule (parsed from FLAGS_chaos), or None.
    Cached on the spec string; setting a new FLAGS_chaos value resets
    the counters (a fresh schedule)."""
    global _cached, _cached_spec
    spec = flags.flag("chaos", "") or ""
    if not str(spec).strip():
        return None
    spec = str(spec)
    with _cache_lock:
        if spec != _cached_spec:
            _cached = FaultSchedule(spec)
            _cached_spec = spec
        return _cached


def reset():
    """Drop the cached schedule (tests: re-arm the same spec string)."""
    global _cached, _cached_spec
    with _cache_lock:
        _cached = None
        _cached_spec = None
    _set_nan_poison(None)


# thin call-site wrappers: one None check when chaos is off -------------
def on_step(step: int):
    s = schedule()
    if s is not None:
        s.on_step(step)


def on_rpc(phase: str, op: str = ""):
    s = schedule()
    if s is not None:
        s.on_rpc(phase, op)


def on_checkpoint_saved(dirname: str):
    s = schedule()
    if s is not None:
        return s.on_checkpoint_saved(dirname)


def on_serving_step(engine, step: int):
    s = schedule()
    if s is not None:
        s.on_serving_step(engine, step)


def on_decode_step():
    s = schedule()
    if s is not None:
        s.on_decode_step()


def take_burst() -> int:
    s = schedule()
    return s.take_burst() if s is not None else 0
