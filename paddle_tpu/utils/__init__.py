from . import flags
