"""PRNG key construction (TPU-first).

JAX's default threefry2x32 generator is counter-based and runs on the
VPU: generating the ~500M random bits a dropout-heavy transformer step
consumes costs real time (measured: ~10% of an ERNIE-base train step on
v5e).  TPUs have a hardware RNG; ``impl="rbg"`` uses it and is an
order of magnitude cheaper for mask generation.

``FLAGS_tpu_prng_impl`` selects the implementation (default ``rbg``).
Only the *stream* changes — the dropout distribution is contractual,
the stream is not (same stance as the reference's cuRAND Philox vs CPU
mt19937 streams, paddle/fluid/operators/dropout_op.cu vs .cc).

Single-device paths (dygraph tracer, Executor) use this helper.  The
multi-device program replays (parallel/data_parallel.py, pipeline.py)
deliberately keep threefry: its output is bit-identical under any
sharding layout, which the DP-vs-single parity oracle relies on; rbg
output may depend on how the array is partitioned.
"""
from __future__ import annotations

import jax

from . import flags


_KNOWN_IMPLS = ("rbg", "unsafe_rbg", "threefry2x32")


def prng_key(seed: int = 0):
    impl = flags._flags.get("FLAGS_tpu_prng_impl", "rbg")
    if impl not in _KNOWN_IMPLS:
        raise ValueError(
            f"FLAGS_tpu_prng_impl={impl!r} is not one of {_KNOWN_IMPLS}")
    try:
        return jax.random.key(int(seed), impl=impl)
    except TypeError:  # old jax without the impl kwarg
        return jax.random.key(int(seed))
