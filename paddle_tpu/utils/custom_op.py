"""Custom operator extension mechanism.

Reference: fluid.load_op_library (framework.py:5388 +
framework/load_op_lib.h) and the example custom op build
(python/paddle/fluid/tests/custom_op/relu_op.cc) — users compile ops
out-of-tree into a shared library and register them at runtime.

Two tiers here, mirroring how the capability splits on TPU:
* ``register_op`` — pure-Python/JAX custom op: supply a lowering (any
  jax-traceable function) and optionally a grad lowering; this is the
  idiomatic TPU path (the kernel JIT-compiles through XLA/Pallas).
* ``load_op_library`` — native C/C++ kernels via a small stable C ABI
  (below); kernels run host-side through ``jax.pure_callback`` with a
  ``custom_vjp`` bridging the backward.  This matches the reference's
  dlopen contract for ops whose kernels are plain CPU code.

Native library ABI (all symbols optional except the first three):
  int         PD_OpCount(void);
  const char* PD_OpName(int i);
  void        PD_OpForward(int i, const float* x, float* y, int64_t n);
  void        PD_OpBackward(int i, const float* x, const float* dy,
                            float* dx, int64_t n);   // optional
Kernels are elementwise float32 (n = element count).
"""
from __future__ import annotations

import ctypes
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["register_op", "load_op_library", "CUSTOM_REGISTERED"]

#: op types registered at runtime through this module (pure-Python
#: register_op and native load_op_library alike).  The memory planner's
#: coverage gate (framework/memory_plan.py memory_audit) consults this:
#: a custom op's memory behavior is its author's contract — the static
#: audit cannot see into user kernels, so they are classified
#: "custom" instead of failing the sweep.
CUSTOM_REGISTERED: set = set()


def register_op(op_type: str, lower: Callable, grad_lower: Callable = None,
                n_outputs: int = 1, no_grad: bool = False):
    """Register a Python custom op usable from layers/programs.

    ``lower(ctx)`` receives the LowerCtx (``ctx.in_("X")``,
    ``ctx.attr``, ``ctx.set_out``).  If ``grad_lower`` is given it is
    registered for ``<op_type>_grad``; otherwise the generic vjp-replay
    grad covers differentiable lowerings automatically.
    """
    from ..ops.registry import op as _op_dec

    _op_dec(op_type, no_grad=no_grad)(lower)
    CUSTOM_REGISTERED.add(op_type)
    if grad_lower is not None:
        _op_dec(op_type + "_grad", no_grad=True)(grad_lower)
        CUSTOM_REGISTERED.add(op_type + "_grad")
    return op_type


class _NativeOpLib:
    def __init__(self, path: str):
        self.lib = ctypes.CDLL(path)
        self.lib.PD_OpCount.restype = ctypes.c_int
        self.lib.PD_OpName.restype = ctypes.c_char_p
        self.lib.PD_OpName.argtypes = [ctypes.c_int]
        self.lib.PD_OpForward.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        self.has_backward = hasattr(self.lib, "PD_OpBackward")
        if self.has_backward:
            self.lib.PD_OpBackward.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

    def forward(self, i: int, x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        y = np.empty_like(x)
        self.lib.PD_OpForward(
            i, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return y

    def backward(self, i: int, x: np.ndarray, dy: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        dy = np.ascontiguousarray(dy, np.float32)
        dx = np.empty_like(x)
        self.lib.PD_OpBackward(
            i, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            dy.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            dx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return dx


def load_op_library(path: str) -> List[str]:
    """reference: fluid.load_op_library (framework.py:5388).  Returns the
    list of op types registered from the library."""
    lib = _NativeOpLib(path)
    names = []
    for i in range(lib.lib.PD_OpCount()):
        name = lib.lib.PD_OpName(i).decode()
        names.append(name)
        _register_native(lib, i, name)
        CUSTOM_REGISTERED.add(name)
        CUSTOM_REGISTERED.add(name + "_grad")
    return names


def _register_native(lib: _NativeOpLib, index: int, name: str):
    from ..ops.registry import op as _op_dec

    def host_fwd(x):
        return lib.forward(index, np.asarray(x))

    if lib.has_backward:
        @jax.custom_vjp
        def fwd_fn(x):
            return jax.pure_callback(
                host_fwd, jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32), x)

        def fwd_rule(x):
            y = fwd_fn(x)
            return y, x

        def bwd_rule(x, dy):
            dx = jax.pure_callback(
                lambda xx, dd: lib.backward(index, np.asarray(xx),
                                            np.asarray(dd)),
                jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32), x, dy)
            return (dx,)

        fwd_fn.defvjp(fwd_rule, bwd_rule)
    else:
        def fwd_fn(x):
            return jax.pure_callback(
                host_fwd, jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32), x)

    def lower(ctx):
        ctx.set_out("Out", fwd_fn(jnp.asarray(ctx.in_("X"),
                                              dtype=jnp.float32)))

    _op_dec(name, no_grad=not lib.has_backward)(lower)


def custom_layer(op_type: str):
    """Layers-style helper for a registered custom op:
    ``y = custom_layer("relu2")(x)``."""
    from ..layer_helper import LayerHelper

    def fn(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out

    return fn
