"""Global flag system.

Reference: paddle/fluid/platform/flags.cc (26+ gflags, read from FLAGS_*
env vars, exposed to Python via fluid.set_flags/get_flags,
pybind/global_value_getter_setter.cc).  Same three-tier shape: env-seeded
defaults, runtime set_flags, strategy dataclasses elsewhere.
"""
from __future__ import annotations

import os
import sys
from typing import Any, Dict

_DEFAULTS: Dict[str, Any] = {
    "FLAGS_check_nan_inf": False,          # flags.cc:44
    "FLAGS_benchmark": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,   # GC threshold — XLA-managed, stat only
    "FLAGS_allocator_strategy": "xla_bfc",  # allocator is XLA's; exposed for parity
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_fraction_of_cpu_memory_to_use": 1.0,   # cpu_info.cc:70
    "FLAGS_initial_cpu_memory_in_mb": 500,        # cpu_info.cc:81
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_enable_parallel_graph": False,
    "FLAGS_sync_nccl_allreduce": True,
    "FLAGS_communicator_max_merge_var_num": 20,
    "FLAGS_communicator_send_queue_size": 20,
    "FLAGS_communicator_independent_recv_thread": True,
    "FLAGS_communicator_send_wait_times": 5,
    "FLAGS_communicator_recv_wait_ms": 50,
    # RPC robustness (reference: flags.cc FLAGS_rpc_deadline /
    # FLAGS_rpc_retry_times, grpc_client.cc deadline handling): a PS
    # client call must complete within deadline ms; transport failures
    # retry up to retry_times with bounded exponential backoff
    # (backoff_ms * 2^attempt, capped at 2000 ms, +/-50% jitter).
    # Mutating calls carry an idempotence key so a retry after a lost
    # reply never double-applies (distributed_ps/update_recorder.py
    # RequestDeduper).
    "FLAGS_rpc_deadline": 180000,
    "FLAGS_rpc_retry_times": 3,
    "FLAGS_rpc_retry_backoff_ms": 50,
    "FLAGS_use_pinned_memory": True,
    "FLAGS_seed": 0,
    "FLAGS_enable_unused_var_check": False,
    "FLAGS_tpu_matmul_precision": "default",  # TPU-native: bf16 matmul control
    "FLAGS_tpu_donate_buffers": True,
    # training-time IR fusion pipeline (reference: build_strategy
    # fuse_bn_act_ops / fuse_bn_add_act_ops); applied by the Executor at
    # compile time on a program clone
    "FLAGS_apply_ir_passes": True,
    # dygraph multi-tensor Adam: flatten all dense f32 param updates
    # into one fused kernel (reference: ir/fuse_optimizer_ops_pass/
    # fuse_adam_op_pass.cc does the same rewrite on the static graph)
    "FLAGS_fuse_optimizer_dygraph": True,
    # PRNG implementation for dropout/random ops on the single-device
    # paths: "rbg" uses the TPU hardware RNG (~10% of an ERNIE step
    # cheaper than threefry mask generation); "threefry2x32" restores
    # jax's default counter-based stream
    "FLAGS_tpu_prng_impl": "rbg",
    # NHWC layout propagation for conv/bn/pool chains (framework/ir.py
    # layout_transform_pass): "auto" enables it when the executor place
    # is an accelerator, "1"/"0" force it on/off everywhere.  "0"
    # restores the NCHW pipeline bit-for-bit.
    "FLAGS_tpu_nhwc": "auto",
    # executor step session: keep donated state device-resident across
    # Executor.run calls (zero scope reads per steady-state step).  Off
    # restores the per-step scope.get rebind path.
    "FLAGS_tpu_step_session": True,
    # profile-ranked Pallas epilogue fusion (framework/ir.py
    # fuse_epilogue_pass): rewrite conv2d->batch_norm(->add)->relu and
    # matmul/mul->elementwise_add->activation chains (fwd AND the
    # matching grad chains) into the fused_conv_bn_act /
    # fused_matmul_bias_act ops, ranked by utils/cost_model.py
    # rank_fusion_candidates.  "auto" enables it when the executor place
    # is an accelerator (like FLAGS_tpu_nhwc); "1"/"0" force on/off.
    # "0" restores the unfused pipeline bit-for-bit.
    "FLAGS_tpu_fuse": "auto",
    # input-pipeline double buffering (executor.py double_buffered_feeds):
    # batch k+1's feed staging (dtype cast + device_put_owned — the
    # donation-safe copy, see executor.device_put_owned) runs on a
    # background thread while step k's dispatch is in flight.  0 stages
    # synchronously on the caller's thread — same values, no overlap.
    "FLAGS_tpu_double_buffer": True,
    # Sharded data parallelism over the 'dp' mesh axis (the Fleet
    # `sharding` strategy analog), staged like fleet sharding_stage /
    # ZeRO:
    #   0  off (default): everything replicated — today's behavior;
    #   1  ZeRO-1: optimizer state (Adam moments / momentum velocities /
    #      the dygraph fused-Adam flat master) shards 1/ndev per device;
    #   2  ZeRO-2: stage 1 + gradients shard — fused grad buckets lower
    #      to reduce-scatter straight into the per-device shard update,
    #      with no full-gradient materialization;
    #   3  ZeRO-3: stage 2 + parameters shard over dp with just-in-time
    #      all-gather at each forward/backward consumer and immediate
    #      discard.
    # Truthy values coerce to stage 1 (the r7 flag was a bool).
    "FLAGS_dp_sharding": 0,
    # coalesced gradient communication (reference:
    # ir/fuse_all_reduce_op_pass.cc + coalesce_grad_tensor_pass.cc):
    # consecutive same-dtype c_allreduce_sum ops bucket up to this many
    # MB of payload and lower to ONE flattened collective.  0 disables
    # the rewrite (one collective per gradient tensor, today's graph).
    # "auto" (r9) derives VARIABLE bucket boundaries from the modeled
    # backward timeline (utils/cost_model.py): buckets are chosen so the
    # serialized collective stream finishes as early as possible —
    # minimizing est. exposed comm rather than bucket count.  Requires
    # FLAGS_dp_comm_overlap (with overlap off, "auto" behaves as the
    # 32 MB default).
    "FLAGS_fuse_grad_size_in_MB": 32.0,
    # compressed allreduce for fused gradient buckets (EQuARX-style,
    # arxiv 2506.17615): "bf16" halves wire bytes by casting the bucket
    # payload to bf16 for transport while accumulating the reduction in
    # f32; "none" (default) keeps full-width f32 allreduce.
    "FLAGS_dp_grad_compress": "none",
    # backward-overlap scheduling for fused gradient buckets (reference:
    # multi_devices_graph_pass backward-op-aware allreduce ordering):
    # order buckets by last-gradient-ready position and issue each
    # bucket's collective right after its last input producer, so bucket
    # 0's collective runs while later layers are still in backward.  Off
    # restores the r7 append-at-last-member schedule.
    "FLAGS_dp_comm_overlap": True,
    # ZeRO-3 parameter-prefetch window (ops): a sharded parameter's
    # all-gather is hoisted this many ops ahead of its first consumer in
    # each direction (forward / backward), deduping the per-consumer
    # gathers into one gather per param per direction with discard after
    # the last consumer — gather layer k+1 while layer k computes.  0
    # restores the r8 just-in-time gather at every consumer.
    "FLAGS_dp_prefetch_depth": 1,
    # cost-model-driven auto-parallel plan search (parallel/
    # plan_search.py, r16): "auto" makes the DP compile path ENUMERATE
    # candidate plans (ZeRO stage x bucket threshold incl. "auto" x
    # prefetch depth incl. per-param autotune x comm overlap), price
    # each with the calibrated cost model's modeled step time, reject
    # candidates whose plan_memory() modeled peak exceeds
    # FLAGS_hbm_budget_mb BEFORE any compile, and run the argmin through
    # the normal verifier-bracketed pass pipeline.  The chosen plan is
    # attached as compiled._plan, gauged in telemetry, and explainable
    # via tools/dp_comm_stats.py --plan.  "" (default) keeps today's
    # flag-driven behavior bit-for-bit: FLAGS_dp_sharding /
    # FLAGS_fuse_grad_size_in_MB / FLAGS_dp_prefetch_depth /
    # FLAGS_dp_comm_overlap apply exactly as set.
    "FLAGS_dp_plan": "",
    # while_loop with a statically-derivable trip count (counter-vs-
    # constant less_than cond, constant-step counter update) lowers to
    # lax.scan: the forward stays on-device and the backward becomes one
    # scan-vjp computation instead of the per-iteration host replay
    # loop.  0 restores the lax.while_loop / host-replay path.
    "FLAGS_while_static_scan": True,
    # deterministic fault injection (utils/chaos.py): a seeded schedule
    # string — e.g. "seed=7;kill@12;rpc_drop=recv@3;trunc_ckpt@1" —
    # that kills the rank at a step, drops/delays RPCs and truncates
    # checkpoint files, reproducibly.  Empty = all hooks are no-ops.
    "FLAGS_chaos": "",
    # unified runtime telemetry (utils/telemetry.py): the process-wide
    # metrics registry the executor / serving engine / PS client publish
    # to.  0 makes every instrument the shared no-op object — no
    # registry writes, no per-call allocation — restoring prior behavior
    # bit-for-bit (host-side bookkeeping only; it never touches program
    # numerics either way, which the telemetry tests pin).
    "FLAGS_telemetry": True,
    # request-scoped distributed tracing (utils/tracing.py): the
    # serving engine records a span tree per request (submit ->
    # queue_wait -> prefill -> decode steps -> preempt/resume cycles ->
    # finish/reject), the PS client injects trace context next to the
    # r11 idempotence key so the server's span joins the same trace,
    # and spans emit as a per-request lane in the unified chrome trace.
    # Off (default): nothing records, nothing allocates — serving token
    # streams and training losses are bit-identical (pinned by test).
    "FLAGS_trace_requests": False,
    # head-based sampling for request traces: the keep/drop decision is
    # a pure crc32 function of (FLAGS_trace_seed, req_id) made once at
    # submit, so a seeded loadgen trace samples the SAME requests on
    # every replay (the r12 determinism contract).  1.0 = every request.
    "FLAGS_trace_sample_rate": 1.0,
    "FLAGS_trace_seed": 0,
    # declared serving SLO targets (utils/telemetry.py SLOTracker):
    # TTFT and per-token latency bounds in ms (0 = target unset — every
    # request counts as within), the SLO objective (fraction of
    # requests that must meet the targets; 1-objective is the error
    # budget the burn rate is measured against) and the rolling
    # request window the burn rate is computed over.  Tools (slo_report
    # / serving_bench) override these per run via
    # telemetry.slo_tracker().configure().
    "FLAGS_slo_ttft_ms": 0.0,
    "FLAGS_slo_token_ms": 0.0,
    "FLAGS_slo_objective": 0.99,
    "FLAGS_slo_window": 256,
    # serving admission/preemption policy (inference/admission.py):
    # "fifo" (default) keeps FIFO admission order, youngest-first
    # preemption and no shedding — byte-identical to the pre-policy
    # engine (token streams, event streams and telemetry counters
    # pinned by test).  "slo_aware" orders admission by remaining SLO
    # slack (declared TTFT target scaled down by the live burn rate
    # from slo_hint(), minus time queued), SHEDS queued requests whose
    # predicted TTFT can no longer meet the target (explicit `shed`
    # outcome: traced root status="shed" +
    # serving_rejects_total{reason="shed"} — distinct from the
    # unservable submit rejection), and preempts the victim with the
    # LEAST lost work (prompt + decoded tokens recomputed on resume)
    # instead of the youngest.  Deterministic for a seeded trace on a
    # deterministic clock (tools/overload_bench.py is the A/B oracle).
    "FLAGS_admission_policy": "fifo",
    # copy-on-write KV prefix caching (inference/kv_cache.py): pages
    # become refcounted and immutable-once-full, full (and partial-tail)
    # prompt pages are indexed by a chained content hash, and a new
    # request's prefill SKIPS every already-cached page of its prompt —
    # the pages map into its block table at refcount+1; the first write
    # into a shared partial page forks it (CoW), frees decrement
    # refcounts and reclaim only at zero, and refcount-0 pages stay in
    # the index as evictable cached pages (deterministic seeded
    # eviction order) until fresh pages run out.  Off (default): the
    # allocator runs the exact r12 FIFO handout — byte-identical
    # (pinned by test).
    "FLAGS_kv_prefix_cache": False,
    # chunked prefill (inference/serving.py): when > 0, a prompt whose
    # uncached suffix exceeds this many tokens prefills in chunks of at
    # most this size, one chunk per engine step, through the normal
    # per-step admission loop — decode admission never stalls behind a
    # long prompt (the max prefill work in any step is bounded by this
    # budget), and prompts larger than the token budget become
    # servable.  Each chunk attends over the pool-resident prefix K/V
    # (the "chunk" program form).  0 (default): monolithic prefill,
    # byte-identical to r18 (pinned by test).
    "FLAGS_prefill_chunk_tokens": 0,
    # speculative decoding (inference/serving.py + spec_decode.py): when
    # > 0, each decode step drafts up to this many candidate tokens per
    # sequence (n-gram prompt-lookup proposer by default, no draft
    # model), scores all K+1 positions in ONE chunk-form verify program
    # call against the pool-resident K/V, accepts the longest agreeing
    # prefix (greedy: exact-argmax match, so greedy spec-decode is
    # token-identical to the monolithic baseline) and truncates the
    # rejected K/V appends in place.  The verify charges accepted+1
    # tokens against the token budget exactly like the monolithic path
    # (zero-accept degrades to baseline step count and accounting).
    # 0 (default): the r20 decode loop runs byte-identically (pinned
    # by test).
    "FLAGS_spec_decode_k": 0,
    # quantized KV page pool (inference/kv_cache.py + ops/paged_ops.py):
    # the serving engine stores K/V pages in this dtype — "bfloat16"
    # halves pool bytes, "int8" quarters them and carries a
    # per-(kv_head, page) absmax scale in a parallel f32 scale pool
    # (~1.6% overhead at page_size=16/head_dim=32).  Every attention
    # read (paged decode kernel + jnp fallback, chunk and spec-verify
    # gathers) dequantizes inline and accumulates in f32; writes
    # quantize in-program (int8: monotone per-page scale with touched-
    # page requant, so append order never rescales untouched pages
    # destructively).  CoW forks copy pages+scales verbatim, truncate
    # leaves surviving scales alone, and the prefix digest is a
    # function of token ids only, so prefix hits stay dtype-
    # independent.  The engine derives num_pages from a fixed byte
    # budget, so the dtype buys 2x/4x pool CAPACITY at the same HBM,
    # not just cheaper bytes.  "float32" (default): byte-identical to
    # the unquantized engine — no scale pool, no extra program vars
    # (pinned by test).
    "FLAGS_kv_cache_dtype": "float32",
    # tensor-parallel decode (inference/serving.py + parallel/
    # tensor_parallel.py): shard the serving decoder over an "mp" mesh
    # axis of this many devices — each device holds 1/tp of the
    # attention heads, MLP width and embedding columns (Megatron
    # placements derived from partition rules), with the two per-block
    # c_allreduce_sum combines inserted by the serving_tp_pass.  The
    # paged KV pool shards on its kv_heads dim, so a fixed PER-DEVICE
    # kv_budget_mb buys tp x more pages (the capacity headline).
    # Greedy decode is token-identical to tp=1 on seeded traces
    # (pinned).  1 (default): single-device engine, byte-identical to
    # the pre-TP serving paths — no mesh, no collectives (pinned by
    # test).
    "FLAGS_serving_tp": 1,
    # in-program sampling (ops/sampling_ops.py): when > 0, decode/
    # prefill/chunk/verify programs end in the sample_token op
    # (temperature + engine-level top-k/top-p) under per-slot RNG lane
    # feeds rng_lane(seed, req_id, position) — seeded traces replay
    # bit-identically and lanes are resume-invariant under preemption
    # (recomputed from position, never carried).  0.0 (default): the
    # programs end in arg_max exactly as before — byte-identical
    # (pinned by test).
    "FLAGS_sample_temperature": 0.0,
    # modeled-HBM budget gate (framework/memory_plan.py): when > 0, the
    # executor / DP compile paths check the static liveness planner's
    # modeled peak against this many MB and WARN naming the peak op and
    # the top live vars; FLAGS_hbm_budget_strict upgrades the warning to
    # MemoryBudgetError.  0 (default) skips the check entirely — the
    # planner still runs (it is pure analysis) but nothing gates on it,
    # and training is bit-identical either way (pinned by test).
    "FLAGS_hbm_budget_mb": 0.0,
    "FLAGS_hbm_budget_strict": False,
    # plan-driven memory relief (framework/ir.py memory_relief_pass):
    # when the modeled peak exceeds FLAGS_hbm_budget_mb, the compile
    # paths rewrite the program to fit — per over-budget activation the
    # pass prices (a) "remat" (replay the producing op before its
    # backward consumer: bit-identical, costs modeled recompute time),
    # (b) "offload" (paired memcpy_d2h/memcpy_h2d staged under the
    # double-buffering window: costs modeled host-link time), and on
    # the DP path (c) a plan escalation (raised ZeRO stage / shrunk
    # prefetch window), picking the cheapest by modeled
    # time-per-byte-saved and re-running plan_memory() after each fix.
    # "remat"/"offload" restrict the menu to that fix; "auto" allows
    # all three.  "off" (default): the pass never runs and the whole
    # pipeline is byte-identical to a relief-less build (pinned by
    # test).
    "FLAGS_memory_relief": "off",
    # numerics observability (framework/numerics.py + framework/ir.py
    # numerics_probe_pass): when on, every compile appends cheap
    # in-program stat reductions (absmax/mean/rms/nonfinite-count) over
    # grad/param/update-role vars — one extra fetched vector per step —
    # feeding the numerics_* telemetry gauges, the HealthMonitor
    # (numerics.health()) and the stats ring the NaN/Inf flight
    # recorder dumps.  0 (default) is bit-identical to the unprobed
    # pipeline: no pass, no extra fetch, no instrument (pinned by
    # test).
    "FLAGS_numerics_probe": False,
    # regex over op TYPES widening the probe beyond role-selected vars:
    # every output of a matching op is probed too (the bisector's
    # per-op stream; e.g. ".*" probes everything on a tiny program)
    "FLAGS_numerics_probe_ops": "",
    # last-K-steps per-var stats ring buffer depth (the flight
    # recorder's post-mortem window)
    "FLAGS_numerics_ring_steps": 8,
    # HealthMonitor loss-spike detector: a finite loss more than
    # spike_factor x the rolling window mean (after 8 warmup steps)
    # trips the monitor
    "FLAGS_numerics_spike_window": 32,
    "FLAGS_numerics_spike_factor": 4.0,
    # NaN/Inf flight recorder (framework/numerics.py record_nan_debris,
    # symmetric to FLAGS_oom_debris_dir): when set, an armed
    # FLAGS_check_nan_inf failure or a HealthMonitor trip dumps the
    # failing op, the stats ring, loss history, telemetry snapshot and
    # chrome trace into a fresh subdirectory here; exceptions propagate
    # unchanged either way.  Empty (default) disables the dump.
    "FLAGS_numerics_debris_dir": "",
    # OOM flight recorder (framework/memory_plan.py record_oom_debris):
    # when set, a RESOURCE_EXHAUSTED caught in the executor step/compile
    # paths dumps the memory plan + telemetry snapshot + profiler trace
    # + measured memory stats into a fresh subdirectory here before
    # re-raising, so a chip OOM is diagnosable post-mortem.  Empty
    # (default) disables the dump; the exception propagates unchanged
    # either way.
    "FLAGS_oom_debris_dir": "",
    # static program verifier gate (framework/verifier.py): snapshot
    # before every IR pass, verify dataflow/registry/layout invariants
    # after, raise a diagnostic naming the pass + op + hazard on
    # violation.  On by default under pytest (a structural gate every
    # pass test inherits); off in production — verification never
    # mutates the program, so 0 restores prior behavior bit-for-bit.
    "FLAGS_verify_passes": "pytest" in sys.modules,
    # static SPMD shard-safety analysis (framework/shard_analysis.py +
    # the shard_safety_pass compile gate): abstract-interpret each
    # compiled program's per-var distribution state (replicated /
    # sharded / shard-variant) and check replication soundness,
    # collectives under divergent control flow, and comm/compute
    # hazards.  Analysis only — ON by default as warnings, and programs
    # without collectives short-circuit, so defaults are bit-identical.
    "FLAGS_shard_safety": True,
    # escalate shard-safety ERROR findings from warnings to a raised
    # VerifyError at compile time (CI / pre-deploy linting posture)
    "FLAGS_shard_safety_strict": False,
}


def nhwc_enabled(place=None) -> bool:
    """Resolve FLAGS_tpu_nhwc against the executor place ("auto" means
    on-accelerator only; truthy forces on, falsy off)."""
    v = flag("tpu_nhwc")
    if isinstance(v, str):
        s = v.strip().lower()
        if s == "auto":
            if place is None:
                return False
            try:
                return place.jax_device().platform != "cpu"
            except Exception:
                return False
        return s in ("1", "true", "yes", "on")
    return bool(v)


def tpu_fuse_enabled(place=None) -> bool:
    """Resolve FLAGS_tpu_fuse against the executor place ("auto" means
    on-accelerator only; truthy forces on, falsy off) — the same
    contract as :func:`nhwc_enabled` so the two fusion levers A/B the
    same way."""
    v = flag("tpu_fuse")
    if isinstance(v, str):
        s = v.strip().lower()
        if s == "auto":
            if place is None:
                return False
            try:
                return place.jax_device().platform != "cpu"
            except Exception:
                return False
        return s in ("1", "true", "yes", "on")
    return bool(v)


def _coerce(cur, val):
    if isinstance(cur, bool):
        return str(val).lower() in ("1", "true", "yes", "on")
    if isinstance(cur, int):
        return int(val)
    if isinstance(cur, float):
        # sentinel string modes ride float-typed flags (e.g.
        # FLAGS_fuse_grad_size_in_MB="auto" selects bucket autotune)
        if isinstance(val, str) and val.strip().lower() == "auto":
            return "auto"
        return float(val)
    return val


def dp_plan_auto() -> bool:
    """True when FLAGS_dp_plan selects the searched auto-parallel plan
    (parallel/plan_search.py) instead of the hand-set flags."""
    v = flag("dp_plan", "")
    return isinstance(v, str) and v.strip().lower() == "auto"


def fuse_grad_mb_auto() -> bool:
    """True when FLAGS_fuse_grad_size_in_MB selects the measurement-
    driven variable-bucket mode."""
    v = flag("fuse_grad_size_in_MB")
    return isinstance(v, str) and v.strip().lower() == "auto"


def fuse_grad_mb_value(default: float = 32.0) -> float:
    """Numeric bucket cap: the flag's value, or `default` in auto mode
    (auto caps nothing — the cost model picks the boundaries)."""
    v = flag("fuse_grad_size_in_MB")
    if isinstance(v, str):
        try:
            return float(v)  # numeric string set through a raw layer
        except ValueError:
            return default  # "auto" (or garbage): cost model decides
    return float(v or 0)


_flags: Dict[str, Any] = {}
for k, v in _DEFAULTS.items():
    env = os.environ.get(k)
    _flags[k] = _coerce(v, env) if env is not None else v

#: frozen process-start values (defaults + FLAGS_* env overrides): the
#: restore point for config layers that reset a flag to "unconfigured"
#: (e.g. fleet DistributedStrategy knobs left at None) — restoring raw
#: _DEFAULTS would silently discard the operator's environment settings
_INITIAL: Dict[str, Any] = dict(_flags)


def set_flags(d: Dict[str, Any]):
    for k, v in d.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        # coerce against the flag's declared (default) type, not the
        # current runtime value: a sentinel string riding a float flag
        # ("auto" on FLAGS_fuse_grad_size_in_MB) must not stop a later
        # numeric set from coercing back to float
        cur = _DEFAULTS.get(k, _flags.get(k))
        _flags[k] = _coerce(cur, v) if cur is not None else v


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    out = {}
    for k in keys:
        kk = k if k.startswith("FLAGS_") else "FLAGS_" + k
        out[k] = _flags.get(kk)
    return out


def flag(name, default=None):
    kk = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _flags.get(kk, default)
