"""Request-scoped distributed tracing (``FLAGS_trace_requests``).

The r13 telemetry layer answers "how is the fleet doing" (aggregate
histograms/counters); this module answers "what happened to THIS
request": a span tree per request — submit → queue-wait → prefill →
each decode-step batch → preempt/resume cycles → finish/reject —
recorded by the serving engine (inference/serving.py), propagated
across the PS RPC wire (distributed_ps/service.py injects
``trace_ctx`` next to the r11 idempotence key; the server records a
server-side span against the SAME trace id), and emitted as a
per-request lane in the unified chrome trace (profiler.py, lane
"request": one pid, one tid row per trace).

Design rules:

* **Determinism** — trace ids and the head-based sampling decision are
  pure functions of ``(FLAGS_trace_seed, req_id)`` (crc32, no process
  RNG), and span ids are allocated sequentially per trace — so a
  seeded loadgen trace replays to an identical *structural* span
  stream (:func:`span_stream` excludes wall-clock fields), matching
  the r12 scheduler-determinism contract.
* **Two clocks per span** — ``t0``/``t1`` carry the engine's LOGICAL
  time (the ``now`` the driver passes to ``step``; the clock loadgen's
  latency report uses, so SLO accounting reconciles exactly), while
  ``wall0``/``wall1`` are ``perf_counter`` stamps for real durations
  in the chrome trace.
* **Cardinality discipline** — per-request values (req id, trace id,
  token counts) live in span ATTRIBUTES, never in telemetry metric
  labels (the registry enforces this: telemetry.LABEL_DENYLIST).
  Exemplars go the other way: a histogram bucket may carry ONE trace
  id (telemetry.Histogram.observe(..., exemplar=...)) linking the p99
  bucket to a pull-up-able trace.
* **Off is free** — with ``FLAGS_trace_requests=0`` (default) every
  entry point short-circuits on one flag check; nothing allocates,
  nothing is recorded, and serving/training behavior is bit-identical
  (pinned by test).

Memory is bounded: the store keeps the most recent
:data:`MAX_TRACES` traces and each trace keeps at most
:data:`MAX_SPANS_PER_TRACE` spans (extra spans count in
``trace.dropped``).  Cross-process note: a server in another process
records its spans into ITS process-local store (same trace id), so a
merged end-to-end view needs both stores/traces; in-process servers
(the test and single-host topology) land in one store directly.
"""
from __future__ import annotations

import contextlib
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from . import flags

__all__ = [
    "Span", "Trace", "TraceStore", "MAX_TRACES", "MAX_SPANS_PER_TRACE",
    "enabled", "sampled", "trace_id_for", "new_trace", "store", "reset",
    "current", "current_span", "use_span", "context_meta", "annotate",
    "server_span", "start_request_trace", "span_stream",
]

#: store keeps this many most-recent traces (older evicted FIFO)
MAX_TRACES = 1024
#: per-trace span bound; extras count in ``trace.dropped``
MAX_SPANS_PER_TRACE = 4096
#: per-span event bound (chaos annotations etc.); extras are dropped —
#: an event source that fires per step must aggregate into an attr
MAX_EVENTS_PER_SPAN = 256

#: one lock for store + span allocation: operations are a few
#: instructions, contention is negligible next to the steps/RPCs being
#: traced
_LOCK = threading.Lock()


def enabled() -> bool:
    """FLAGS_trace_requests resolved at call time (runtime-toggleable)."""
    return bool(flags.flag("trace_requests", False))


def _crc(s: str) -> int:
    return zlib.crc32(s.encode()) & 0xFFFFFFFF


def sampled(req_key, seed: Optional[int] = None,
            rate: Optional[float] = None) -> bool:
    """Head-based sampling decision, made ONCE at submit and
    deterministic in (seed, req_key): crc32-hash the pair into [0, 1)
    and compare against FLAGS_trace_sample_rate — the same seeded
    loadgen trace samples the same requests on every replay."""
    if rate is None:
        try:
            rate = float(flags.flag("trace_sample_rate", 1.0))
        except (TypeError, ValueError):
            rate = 1.0
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    if seed is None:
        seed = int(flags.flag("trace_seed", 0) or 0)
    return _crc(f"{seed}:{req_key}") / 4294967296.0 < rate


def trace_id_for(req_key, seed: Optional[int] = None) -> str:
    """Deterministic trace id: readable req key + seeded crc suffix."""
    if seed is None:
        seed = int(flags.flag("trace_seed", 0) or 0)
    return f"req-{req_key}-{_crc(f'{seed}:{req_key}'):08x}"


class Span:
    """One node of a request's span tree.  ``t0``/``t1`` logical time,
    ``wall0``/``wall1`` perf_counter; ``events`` are zero-duration
    annotations (chaos injections land here)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "wall0", "wall1", "attrs", "events")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, t0: float,
                 wall0: float, attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1: Optional[float] = None
        self.wall0 = wall0
        self.wall1: Optional[float] = None
        self.attrs: dict = dict(attrs or {})
        self.events: List[tuple] = []

    @property
    def ended(self) -> bool:
        return self.wall1 is not None

    def wall_duration(self) -> float:
        return max((self.wall1 or self.wall0) - self.wall0, 0.0)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "t0": self.t0, "t1": self.t1,
            "wall0": self.wall0, "wall1": self.wall1,
            "attrs": dict(self.attrs),
            "events": [{"name": n, "attrs": dict(a)}
                       for n, a in self.events],
        }


class Trace:
    """One request's span list + bookkeeping.  Span ids are allocated
    sequentially under the module lock, so a deterministic scheduling
    sequence yields a deterministic span stream."""

    def __init__(self, trace_id: str, req_id=None):
        self.trace_id = trace_id
        self.req_id = req_id
        self.spans: List[Span] = []
        self.finished = False
        self.dropped = 0
        self._next = 1
        # chrome-trace row: one tid per trace inside the request lane's
        # pid (stable across client/server threads in one process)
        self.lane_tid = (_crc(trace_id) & 0x3FFFFFFF) or 1
        # engine bookkeeping (inference/serving.py): the open root span
        # and the currently-open wait span (queue_wait or preempted)
        self._root: Optional[Span] = None
        self._wait: Optional[Span] = None

    # ------------------------------------------------------------------
    def start(self, name: str, t: float = 0.0, parent=None,
              attrs: Optional[dict] = None) -> Span:
        """Open a span (ended later via :meth:`end`).  ``parent`` may be
        a Span or a span-id string; None makes a root-level span."""
        pid = parent.span_id if isinstance(parent, Span) else parent
        with _LOCK:
            sid = f"s{self._next}"
            self._next += 1
            span = Span(self.trace_id, sid, pid, name, t,
                        time.perf_counter(), attrs)
            if len(self.spans) < MAX_SPANS_PER_TRACE:
                self.spans.append(span)
            else:
                self.dropped += 1
        return span

    def end(self, span: Optional[Span], t: Optional[float] = None,
            attrs: Optional[dict] = None):
        """Close a span (idempotent: a second end is a no-op) and emit
        its chrome-trace event when a profiler session is live."""
        if span is None or span.ended:
            return
        span.t1 = span.t0 if t is None else t
        span.wall1 = time.perf_counter()
        if attrs:
            span.attrs.update(attrs)
        _emit(self, span)

    def add(self, name: str, t0: float = 0.0, t1: Optional[float] = None,
            wall0: Optional[float] = None, wall1: Optional[float] = None,
            parent=None, attrs: Optional[dict] = None) -> Span:
        """Record an already-timed span (the engine wraps core
        prefill/decode calls and retro-records their wall bounds)."""
        span = self.start(name, t=t0, parent=parent, attrs=attrs)
        if wall0 is not None:
            span.wall0 = wall0
        span.t1 = t0 if t1 is None else t1
        span.wall1 = time.perf_counter() if wall1 is None else wall1
        _emit(self, span)
        return span

    def annotate(self, span: Optional[Span], name: str,
                 attrs: Optional[dict] = None):
        """Zero-duration event ON a span (chaos injections): shows up
        in the span's ``events`` list and in the chrome args as a
        comma-joined name list.  Bounded per span
        (:data:`MAX_EVENTS_PER_SPAN`)."""
        if span is not None and len(span.events) < MAX_EVENTS_PER_SPAN:
            span.events.append((name, dict(attrs or {})))

    def finish(self):
        self.finished = True

    def spans_named(self, name: str) -> List[Span]:
        return [s for s in self.spans if s.name == name]


def _emit(trace: Trace, span: Span):
    """Span -> chrome-trace complete event on the per-request lane
    (profiler lane "request", tid = the trace's row).  JSON-safe attrs
    ride along as args; no-op without a live profiler session."""
    from .. import profiler

    if not profiler.is_profiler_enabled():
        return
    args = {"trace": trace.trace_id, "span": span.span_id,
            "parent": span.parent_id or "",
            "req": "" if trace.req_id is None else str(trace.req_id)}
    for k, v in span.attrs.items():
        if isinstance(v, (bool, int, float, str)):
            args[k] = v
    if span.events:
        args["events"] = ",".join(n for n, _ in span.events)
    profiler.complete_event(span.name, cat="request", ts=span.wall0,
                            dur=span.wall_duration(),
                            tid=trace.lane_tid, args=args)


class TraceStore:
    """Process-global bounded trace table (most recent MAX_TRACES)."""

    def __init__(self):
        self._traces: "OrderedDict[str, Trace]" = OrderedDict()

    def register(self, trace: Trace) -> Trace:
        with _LOCK:
            while len(self._traces) >= MAX_TRACES:
                self._traces.popitem(last=False)
            self._traces[trace.trace_id] = trace
        return trace

    def get(self, trace_id: str) -> Optional[Trace]:
        with _LOCK:
            return self._traces.get(trace_id)

    def get_or_create(self, trace_id: str, req_id=None) -> Trace:
        """The server-side entry point: attach to the client's trace if
        it lives in THIS process (single-host topology, tests), else
        create a process-local holder under the same trace id."""
        with _LOCK:
            tr = self._traces.get(trace_id)
        if tr is not None:
            return tr
        return self.register(Trace(trace_id, req_id))

    def traces(self) -> List[Trace]:
        with _LOCK:
            return list(self._traces.values())

    def finished_traces(self) -> List[Trace]:
        with _LOCK:
            return [t for t in self._traces.values() if t.finished]

    def reset(self):
        with _LOCK:
            self._traces.clear()


_STORE = TraceStore()


def store() -> TraceStore:
    return _STORE


def reset():
    """Drop every recorded trace (tests / fresh measurement windows)."""
    _STORE.reset()


def new_trace(req_id) -> Trace:
    """Create + register a trace with the deterministic id for req_id."""
    return _STORE.register(Trace(trace_id_for(req_id), req_id))


# -- context propagation (thread-local span stack) -------------------------
_ctx = threading.local()


def _stack() -> list:
    st = getattr(_ctx, "stack", None)
    if st is None:
        st = _ctx.stack = []
    return st


def current() -> Optional[Tuple[Trace, Span]]:
    st = _stack()
    return st[-1] if st else None


def current_span() -> Optional[Span]:
    c = current()
    return c[1] if c else None


@contextlib.contextmanager
def use_span(trace: Trace, span: Span):
    """Make (trace, span) the thread's current context — RPC client
    spans and chaos annotations attach to whatever is current."""
    _stack().append((trace, span))
    try:
        yield span
    finally:
        _stack().pop()


def context_meta() -> Optional[dict]:
    """The wire form of the current context ({trace_id, span_id}) —
    what PSClient injects next to the idempotence key."""
    c = current()
    if c is None:
        return None
    return {"trace_id": c[0].trace_id, "span_id": c[1].span_id}


def annotate(name: str, attrs: Optional[dict] = None):
    """Event on the current span, if any (chaos hook entry point)."""
    c = current()
    if c is not None:
        c[0].annotate(c[1], name, attrs)


def server_span(name: str, ctx: dict,
                attrs: Optional[dict] = None) -> Tuple[Trace, Span]:
    """Server-side span from a wire ``trace_ctx``: attaches to the
    originating trace (same process) or a local holder with the same
    trace id, parented on the client's span id."""
    tr = _STORE.get_or_create(str(ctx.get("trace_id")))
    parent = str(ctx.get("span_id") or "") or None
    return tr, tr.start(name, parent=parent, attrs=attrs)


@contextlib.contextmanager
def start_request_trace(name: str, req_id, t: float = 0.0,
                        attrs: Optional[dict] = None):
    """Explicit trace for non-serving callers (training loops, tools):
    opens a root span and makes it current, so PS RPCs issued inside
    the block join the trace.  Bypasses sampling — an explicit trace
    was asked for."""
    tr = new_trace(req_id)
    root = tr.start(name, t=t, attrs=attrs)
    tr._root = root
    with use_span(tr, root):
        try:
            yield tr
        finally:
            tr.end(root, t=t)
            tr.finish()


def span_stream(traces: Optional[List[Trace]] = None) -> list:
    """Canonical STRUCTURAL event stream for determinism tests: per
    trace, each span's (name, parent-name, logical t0/t1, sorted attrs,
    event names) in record order — wall-clock fields excluded (they
    differ run to run), logical fields kept (the engine's ``now`` is
    part of the replayed schedule)."""
    ts = _STORE.traces() if traces is None else traces
    out = []
    for tr in ts:
        names = {s.span_id: s.name for s in tr.spans}
        out.append((tr.req_id, tr.trace_id, tr.finished, tuple(
            (s.name, names.get(s.parent_id), s.t0, s.t1,
             tuple(sorted((k, str(v)) for k, v in s.attrs.items())),
             tuple(n for n, _ in s.events))
            for s in tr.spans)))
    return out
