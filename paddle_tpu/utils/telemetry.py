"""Unified runtime telemetry: one process-wide metrics registry.

Every runtime built so far reported its own numbers its own way — the
executor through host ``RecordEvent``s, the serving engine and KV pool
through ad-hoc ``stats()`` dicts, the PS client through ``n_rpc`` /
``retry_count()``.  This module is the one layer they all publish to
(reference intent: *End-to-end Adaptive Distributed Training on
PaddlePaddle*, arXiv 2112.02752 — runtime decisions driven by measured
profiles need the measurements to exist in one queryable place).

Three instrument kinds, Prometheus-shaped:

* :class:`Counter` — monotonically increasing float (``inc``);
* :class:`Gauge` — set-to-current-value float (``set``/``inc``);
* :class:`Histogram` — fixed log-spaced buckets (4 per decade,
  1 µs … 1000 s: latency-scale events land mid-range) with ``sum`` and
  ``count``, plus quantile *bracketing* (``quantile_bounds``) so a
  reported p50/p99 carries its bucket-resolution error bars instead of
  a false-precision point value.

Instruments are **labeled families**: ``counter("ps_rpc_total",
labels=("op",)).labels(op="pull_dense").inc()``.  Label cardinality is
bounded per family (:data:`MAX_SERIES`); combinations past the bound
collapse into one shared overflow series — an unbounded-cardinality bug
costs one series, never the process.

Gating — ``FLAGS_telemetry`` (default on): when off, the module-level
factories return the shared :data:`NOOP` instrument, whose every method
is a no-op returning ``NOOP`` itself.  No allocation happens per call on
the off path, and no registry state is touched, so ``FLAGS_telemetry=0``
restores prior behavior bit-for-bit (pinned by test).

``snapshot()`` returns one JSON-able dict (the ``telemetry`` section
bench.py / tools/serving_bench.py append to their BENCH artifacts);
``to_prometheus()`` renders the standard text exposition.
"""
from __future__ import annotations

import math
import threading
from bisect import bisect_right
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "NOOP", "MAX_SERIES",
    "LABEL_DENYLIST", "SLOTargets", "SLOTracker", "UNSET",
    "enabled", "registry", "counter", "gauge", "histogram",
    "snapshot", "to_prometheus", "default_buckets",
    "slo_tracker", "reset_slo",
]

#: per-family bound on distinct label combinations; the 65th and later
#: combinations share one overflow series (label values all "~overflow")
MAX_SERIES = 64

#: label keys the registry REJECTS at family creation: per-request
#: identifiers mint one series per request — unbounded cardinality by
#: construction (the overflow series would merely hide it).  Per-request
#: values belong in span attributes (utils/tracing.py); a histogram
#: bucket may carry ONE trace id as an exemplar instead.
LABEL_DENYLIST = frozenset({
    "request_id", "req_id", "req", "trace_id", "span_id",
})

#: label-values tuple of the shared overflow series
OVERFLOW = "~overflow"


def enabled() -> bool:
    """FLAGS_telemetry resolved at call time (runtime-toggleable)."""
    from .flags import flag

    return bool(flag("telemetry", True))


class _Noop:
    """The shared off-path instrument: every method is a no-op and
    ``labels()`` returns the same singleton, so an instrumented call
    site costs one flag check and zero allocations when telemetry is
    off."""

    __slots__ = ()

    def inc(self, value=1.0):
        pass

    def set(self, value):
        pass

    def observe(self, value, exemplar=None):
        pass

    def labels(self, **kv):
        return self

    def get(self):
        return 0.0


NOOP = _Noop()


def default_buckets() -> List[float]:
    """Fixed log-spaced bucket upper bounds in seconds: 4 per decade
    from 1e-6 to 1e+3 (37 edges; one implicit +inf overflow bucket).
    Shared by every histogram so exposition rows line up."""
    return [10.0 ** (-6 + i / 4.0) for i in range(37)]


_DEFAULT_BUCKETS = tuple(default_buckets())


class _Child:
    """One labeled series.  All mutation goes through the family lock —
    increments are a few instructions, contention is negligible next to
    the step/RPC work being measured."""

    __slots__ = ("_lock", "_labels")

    def __init__(self, lock, labels: Tuple[str, ...]):
        self._lock = lock
        self._labels = labels


class Counter(_Child):
    __slots__ = ("_value",)

    def __init__(self, lock, labels):
        super().__init__(lock, labels)
        self._value = 0.0

    def inc(self, value: float = 1.0):
        if value < 0:
            raise ValueError("Counter.inc value must be >= 0")
        with self._lock:
            self._value += value

    def get(self) -> float:
        return self._value


class Gauge(_Child):
    __slots__ = ("_value",)

    def __init__(self, lock, labels):
        super().__init__(lock, labels)
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0):
        with self._lock:
            self._value += value

    def get(self) -> float:
        return self._value


class Histogram(_Child):
    __slots__ = ("_edges", "_counts", "_sum", "_count", "_min", "_max",
                 "_exemplars", "_nonfinite")

    def __init__(self, lock, labels, edges=_DEFAULT_BUCKETS):
        super().__init__(lock, labels)
        self._edges = edges
        self._counts = [0] * (len(edges) + 1)  # last = +inf overflow
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        # non-finite observations land HERE, never in the buckets:
        # bisect_right(edges, nan) files NaN into an arbitrary bucket
        # and one NaN makes _sum/_min/_max NaN forever, silently
        # poisoning every later quantile bracket.  (SLOTracker
        # legitimately feeds NaN TTFTs for zero-token requests.)
        self._nonfinite = 0
        # bucket index -> last exemplar (a trace id): the histogram ->
        # trace link, one string per bucket — bounded by construction
        self._exemplars: Dict[int, str] = {}

    def observe(self, value: float, exemplar: Optional[str] = None):
        v = float(value)
        if not math.isfinite(v):
            with self._lock:
                self._nonfinite += 1
            return
        i = bisect_right(self._edges, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if exemplar is not None:
                self._exemplars[i] = str(exemplar)

    def get(self) -> float:
        """Mean observation (the scalar view other kinds expose)."""
        return self._sum / self._count if self._count else 0.0

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def nonfinite(self) -> int:
        """Observations excluded from buckets/sum for being NaN/Inf."""
        return self._nonfinite

    def _bucket_of_rank(self, k: int) -> int:
        """Index of the bucket holding the k-th (0-based) observation."""
        c = 0
        for i, n in enumerate(self._counts):
            c += n
            if k < c:
                return i
        return len(self._counts) - 1

    def _bounds_of_bucket(self, i: int) -> Tuple[float, float]:
        lo = self._edges[i - 1] if i > 0 else 0.0
        hi = self._edges[i] if i < len(self._edges) else math.inf
        # tighten by the actually observed extremes (exact, cheap)
        if self._count:
            lo = max(lo, self._min) if self._min <= hi else lo
            hi = min(hi, self._max) if self._max >= lo else hi
        return lo, hi

    def quantile_bounds(self, q: float) -> Tuple[float, float]:
        """(lo, hi) provably bracketing the q-quantile under the
        linear-interpolation rank convention numpy uses: lo is the
        lower edge of the bucket holding the floor-rank sample, hi the
        upper edge of the bucket holding the ceil-rank sample.  The
        exact sample-level quantile (utils/loadgen.py's percentile)
        always lies inside — the property the serving p50/p99 test
        pins.  (nan, nan) when empty."""
        with self._lock:
            n = self._count
            if n == 0:
                return (math.nan, math.nan)
            pos = min(max(q, 0.0), 1.0) * (n - 1)
            lo_b = self._bucket_of_rank(int(math.floor(pos)))
            hi_b = self._bucket_of_rank(int(math.ceil(pos)))
            return (self._bounds_of_bucket(lo_b)[0],
                    self._bounds_of_bucket(hi_b)[1])

    def quantile(self, q: float) -> float:
        """Point estimate: geometric midpoint of the bracketing bounds
        (log-spaced buckets make the geometric mean the unbiased
        choice); falls back to the finite edge when one side is 0/inf."""
        lo, hi = self.quantile_bounds(q)
        if math.isnan(lo):
            return math.nan
        if lo > 0 and math.isfinite(hi):
            return math.sqrt(lo * hi)
        return lo if not math.isfinite(hi) else hi

    def exemplar_for_quantile(self, q: float) -> Optional[str]:
        """The trace id linked to the bucket holding the q-quantile
        sample — "the p99 bucket names a trace you can pull up".  Falls
        back to the nearest bucket with an exemplar when that exact
        bucket recorded none (samples may be observed exemplar-less)."""
        with self._lock:
            if not self._count or not self._exemplars:
                return None
            pos = min(max(q, 0.0), 1.0) * (self._count - 1)
            b = self._bucket_of_rank(int(math.ceil(pos)))
            if b in self._exemplars:
                return self._exemplars[b]
            for i in range(b - 1, -1, -1):
                if i in self._exemplars:
                    return self._exemplars[i]
            for i in range(b + 1, len(self._counts)):
                if i in self._exemplars:
                    return self._exemplars[i]
            return None


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """A named instrument family: fixed kind + label names, bounded set
    of labeled children."""

    def __init__(self, name: str, kind: str, help_: str,
                 label_names: Tuple[str, ...]):
        self.name = name
        self.kind = kind
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not label_names:  # unlabeled: the family IS its only child
            self._default = self._make(())
        else:
            self._default = None

    def _make(self, values: Tuple[str, ...]) -> _Child:
        return _KINDS[self.kind](self._lock, values)

    def labels(self, **kv) -> _Child:
        if not self.label_names:
            if kv:
                raise ValueError(f"{self.name} declares no labels")
            return self._only()
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.label_names)}")
        values = tuple(str(kv[k]) for k in self.label_names)
        with self._lock:
            child = self._children.get(values)
            if child is None:
                if len(self._children) >= MAX_SERIES:
                    values = (OVERFLOW,) * len(self.label_names)
                    child = self._children.get(values)
                    if child is None:
                        child = self._make(values)
                        self._children[values] = child
                else:
                    child = self._make(values)
                    self._children[values] = child
            return child

    # unlabeled convenience: the family proxies its single child
    def _only(self) -> _Child:
        if self._default is None:
            raise ValueError(
                f"{self.name} is labeled {self.label_names}: call "
                f".labels(...) first")
        return self._default

    def inc(self, value: float = 1.0):
        return self._only().inc(value)

    def set(self, value: float):
        return self._only().set(value)

    def observe(self, value: float, exemplar=None):
        return self._only().observe(value, exemplar)

    def get(self):
        return self._only().get()

    # delegated Histogram views (unlabeled convenience)
    @property
    def count(self):
        return self._only().count

    @property
    def sum(self):
        return self._only().sum

    @property
    def nonfinite(self):
        return self._only().nonfinite

    def quantile(self, q: float):
        return self._only().quantile(q)

    def quantile_bounds(self, q: float):
        return self._only().quantile_bounds(q)

    def exemplar_for_quantile(self, q: float):
        return self._only().exemplar_for_quantile(q)

    def series(self) -> Dict[Tuple[str, ...], _Child]:
        with self._lock:
            if self._default is not None:  # unlabeled family
                return {(): self._default}
            return dict(self._children)

    def reset(self):
        with self._lock:
            for values in list(self._children):
                self._children[values] = self._make(values)
            if self._default is not None:
                self._default = self._make(())


class Registry:
    """Process-wide family table.  ``counter``/``gauge``/``histogram``
    are idempotent get-or-create (re-declaring with a different kind or
    label set is an error — two subsystems fighting over one name is a
    bug worth surfacing)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help_: str,
                labels: Sequence[str]) -> _Family:
        label_names = tuple(labels)
        bad = sorted(l for l in label_names if l in LABEL_DENYLIST)
        if bad:
            raise ValueError(
                f"telemetry instrument {name!r}: label key(s) {bad} are "
                f"per-request identifiers — one series per request is "
                f"unbounded cardinality.  Put per-request values in span "
                f"attributes (utils/tracing.py) or link a trace id as a "
                f"histogram exemplar instead.")
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help_, label_names)
                self._families[name] = fam
                return fam
        if fam.kind != kind or fam.label_names != label_names:
            raise ValueError(
                f"telemetry instrument {name!r} re-declared as "
                f"{kind}{label_names} (was {fam.kind}{fam.label_names})")
        return fam

    def counter(self, name, help="", labels=()) -> _Family:
        return self._family(name, "counter", help, labels)

    def gauge(self, name, help="", labels=()) -> _Family:
        return self._family(name, "gauge", help, labels)

    def histogram(self, name, help="", labels=()) -> _Family:
        return self._family(name, "histogram", help, labels)

    def reset(self):
        """Zero every series, keep the families (the serving bench's
        between-warmup-and-measured zeroing, registry edition)."""
        with self._lock:
            fams = list(self._families.values())
        for f in fams:
            f.reset()

    def clear(self):
        """Drop everything (tests: a pristine registry)."""
        with self._lock:
            self._families.clear()

    # -- export ------------------------------------------------------------
    def snapshot(self) -> Dict:
        """One JSON-able dict: {name: {type, help, labels, series: [...]}}.
        Histogram series carry cumulative bucket counts as [le, count]
        pairs (Prometheus ``le`` convention) plus sum/count/min/max."""
        out: Dict[str, dict] = {}
        with self._lock:
            fams = dict(self._families)
        for name, fam in sorted(fams.items()):
            rows = []
            for values, child in sorted(fam.series().items()):
                row = {"labels": dict(zip(fam.label_names, values))}
                if fam.kind == "histogram":
                    cum = 0
                    buckets = []
                    for i, c in enumerate(child._counts):
                        cum += c
                        le = (child._edges[i] if i < len(child._edges)
                              else math.inf)
                        if c or le is math.inf:
                            buckets.append([le if math.isfinite(le)
                                            else "+Inf", cum])
                    row.update({
                        "count": child._count,
                        "sum": child._sum,
                        "min": (child._min if child._count else None),
                        "max": (child._max if child._count else None),
                        "buckets": buckets,
                    })
                    if child._nonfinite:
                        # only when observed: a zero field on every row
                        # would churn existing snapshot consumers
                        row["nonfinite"] = child._nonfinite
                    # copy under the child lock: a concurrent observe
                    # may INSERT a bucket key (the other lockless reads
                    # here are fixed-size lists/scalars)
                    with child._lock:
                        exemplars = dict(child._exemplars)
                    if exemplars:
                        row["exemplars"] = {
                            (repr(child._edges[i])
                             if i < len(child._edges) else "+Inf"): ex
                            for i, ex in sorted(exemplars.items())}
                else:
                    row["value"] = child.get()
                rows.append(row)
            out[name] = {"type": fam.kind, "help": fam.help,
                         "labels": list(fam.label_names), "series": rows}
        return out

    def to_prometheus(self) -> str:
        """Standard text exposition (histograms: _bucket/_sum/_count)."""
        lines: List[str] = []
        with self._lock:
            fams = dict(self._families)
        for name, fam in sorted(fams.items()):
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for values, child in sorted(fam.series().items()):
                lab = ",".join(f'{k}="{v}"'
                               for k, v in zip(fam.label_names, values))
                if fam.kind == "histogram":
                    cum = 0
                    for i, c in enumerate(child._counts):
                        cum += c
                        le = (repr(child._edges[i])
                              if i < len(child._edges) else "+Inf")
                        sep = "," if lab else ""
                        lines.append(
                            f'{name}_bucket{{{lab}{sep}le="{le}"}} {cum}')
                    suffix = f"{{{lab}}}" if lab else ""
                    lines.append(f"{name}_sum{suffix} {child._sum}")
                    lines.append(f"{name}_count{suffix} {child._count}")
                    if child._nonfinite:
                        lines.append(f"{name}_nonfinite{suffix} "
                                     f"{child._nonfinite}")
                else:
                    suffix = f"{{{lab}}}" if lab else ""
                    lines.append(f"{name}{suffix} {child.get()}")
        return "\n".join(lines) + ("\n" if lines else "")


_REGISTRY = Registry()


def registry() -> Registry:
    """The process registry (always real — gating lives in the
    module-level factories below, so exporters can read a snapshot even
    while instrumentation is switched off)."""
    return _REGISTRY


# -- gated factories: THE instrumentation surface --------------------------
def counter(name, help="", labels=()):
    return _REGISTRY.counter(name, help, labels) if enabled() else NOOP


def gauge(name, help="", labels=()):
    return _REGISTRY.gauge(name, help, labels) if enabled() else NOOP


def histogram(name, help="", labels=()):
    return _REGISTRY.histogram(name, help, labels) if enabled() else NOOP


def snapshot() -> Dict:
    return _REGISTRY.snapshot()


def to_prometheus() -> str:
    return _REGISTRY.to_prometheus()


# ==========================================================================
# SLO accounting (r17): error-budget burn rate + goodput over finished
# serving requests
# ==========================================================================
@dataclass(frozen=True)
class SLOTargets:
    """Declared serving SLO: latency bounds (None = unset, always met),
    the objective (fraction of requests that must meet the bounds —
    1-objective is the error budget) and the rolling request window the
    burn rate is measured over."""

    ttft_s: Optional[float] = None
    token_s: Optional[float] = None
    objective: float = 0.99
    window: int = 256

    def to_dict(self) -> dict:
        return {"ttft_s": self.ttft_s, "token_s": self.token_s,
                "objective": self.objective, "window": self.window}


#: configure() sentinel: "argument not given — inherit the flag value"
#: (distinct from an explicit None/0, which DISARMS the target)
UNSET = object()


def _flag_targets() -> SLOTargets:
    from .flags import flag

    ttft = float(flag("slo_ttft_ms", 0.0) or 0.0) / 1e3
    token = float(flag("slo_token_ms", 0.0) or 0.0) / 1e3
    return SLOTargets(
        ttft_s=ttft or None, token_s=token or None,
        objective=float(flag("slo_objective", 0.99) or 0.99),
        window=max(int(flag("slo_window", 256) or 256), 1))


class SLOTracker:
    """Live SLO accounting over finished requests, fed by the serving
    engines at finish time (inference/serving.py) with the exact
    latency convention utils/loadgen.py reports — TTFT is the first
    token's gap from arrival, decode gaps are the inter-token gaps of
    the request's FINAL run — so the tracker's goodput reconciles
    exactly with loadgen's independently computed per-request numbers
    (pinned by tools/slo_report.py --quick).

    * a request is **within SLO** when its TTFT meets the TTFT target
      AND every decode gap meets the per-token target (unset targets
      always met);
    * **goodput** counts requests and tokens served within SLO vs
      total (token granularity: the first token judged against the
      TTFT target, each decode token against the per-token target);
    * **burn rate** = (violating fraction of the last ``window``
      finished requests) / (1 - objective): 1.0 means the error budget
      drains exactly at the sustainable rate, >1 means it drains
      faster.

    ``admission_hint()`` is the read hook the SLO-aware admission
    policy (inference/admission.py, r18) drives its slack ordering and
    shed threshold from; the default ``fifo`` policy never reads it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._targets = _flag_targets()
        self._window: deque = deque(maxlen=self._targets.window)
        self._req_total = 0
        self._req_within = 0
        self._tok_total = 0
        self._tok_within = 0
        self._prefix_hit_tokens = 0
        self._prompt_tokens = 0

    # ------------------------------------------------------------------
    def configure(self, ttft_s=UNSET, token_s=UNSET, objective=UNSET,
                  window=UNSET) -> "SLOTracker":
        """Declare targets for the next measurement window and zero the
        accounting.  Omitted arguments inherit the FLAGS_slo_* values;
        an EXPLICIT ``None``/``0`` target disarms it even when the flag
        armed one (the tools' "0 = unset" CLI contract)."""
        base = _flag_targets()
        with self._lock:
            self._targets = SLOTargets(
                ttft_s=base.ttft_s if ttft_s is UNSET else (ttft_s or None),
                token_s=(base.token_s if token_s is UNSET
                         else (token_s or None)),
                objective=(base.objective if objective is UNSET or not
                           objective else float(objective)),
                window=(base.window if window is UNSET or not window
                        else int(window)))
            self._window = deque(maxlen=max(self._targets.window, 1))
            self._zero_locked()
        return self

    def reset(self):
        """Zero the accounting, keep the declared targets (the
        between-warmup-and-measured reset serving_bench does)."""
        with self._lock:
            self._window.clear()
            self._zero_locked()

    def _zero_locked(self):
        self._req_total = self._req_within = 0
        self._tok_total = self._tok_within = 0
        self._prefix_hit_tokens = self._prompt_tokens = 0

    @property
    def targets(self) -> SLOTargets:
        return self._targets

    # ------------------------------------------------------------------
    def observe_request(self, req_id, ttft_s: float,
                        decode_gaps: Sequence[float],
                        trace_id: Optional[str] = None,
                        prefix_hit_tokens: int = 0,
                        prompt_tokens: int = 0) -> bool:
        """One finished request.  ``ttft_s`` may be NaN (zero-token
        request) — it then fails an armed TTFT target (a request that
        never produced its first token did not meet it).
        ``prefix_hit_tokens``/``prompt_tokens`` (r19) aggregate the
        prefix-cache hit ratio the report/admission hint expose — a
        high ratio means admission is cheap (prefills mostly skip), the
        context a burn-rate-driven policy reads next to the burn."""
        t = self._targets
        has_first = ttft_s == ttft_s  # not NaN
        ok_ttft = t.ttft_s is None or (has_first and ttft_s <= t.ttft_s)
        if t.token_s is None:
            ok_gaps, tok_gap_within = True, len(decode_gaps)
        else:
            tok_gap_within = sum(1 for g in decode_gaps if g <= t.token_s)
            ok_gaps = tok_gap_within == len(decode_gaps)
        within = bool(ok_ttft and ok_gaps)
        ntok = (1 if has_first else 0) + len(decode_gaps)
        ntok_within = (1 if (has_first and ok_ttft) else 0) + tok_gap_within
        with self._lock:
            self._req_total += 1
            self._req_within += within
            self._tok_total += ntok
            self._tok_within += ntok_within
            self._prefix_hit_tokens += int(prefix_hit_tokens)
            self._prompt_tokens += int(prompt_tokens)
            self._window.append(within)
            burn = self._burn_locked()
        # registry mirrors (gated like every instrument; per-request
        # identity stays OUT of the labels — the trace id travels as a
        # histogram exemplar from the engine's latency observations)
        counter("slo_requests_total",
                "finished requests judged against the SLO").inc()
        counter("slo_requests_within_slo_total",
                "finished requests that met every armed target").inc(
                    1.0 if within else 0.0)
        counter("slo_tokens_total",
                "tokens judged against the SLO").inc(ntok)
        counter("slo_tokens_within_slo_total",
                "tokens within their latency target").inc(ntok_within)
        gauge("slo_burn_rate",
              "rolling-window error-budget burn rate (1.0 = budget "
              "drains at exactly the sustainable rate)").set(burn)
        return within

    def _burn_locked(self) -> float:
        if not self._window:
            return 0.0
        budget = max(1.0 - self._targets.objective, 1e-9)
        viol = 1.0 - (sum(self._window) / len(self._window))
        return viol / budget

    def burn_rate(self) -> float:
        with self._lock:
            return self._burn_locked()

    def goodput(self) -> Dict:
        with self._lock:
            return {
                "requests_total": self._req_total,
                "requests_within_slo": self._req_within,
                "request_goodput": (self._req_within / self._req_total
                                    if self._req_total else 1.0),
                "tokens_total": self._tok_total,
                "tokens_within_slo": self._tok_within,
                "token_goodput": (self._tok_within / self._tok_total
                                  if self._tok_total else 1.0),
            }

    def prefix_hit_ratio(self) -> float:
        """Fraction of finished requests' prompt tokens served from
        cached prefix pages (0.0 with the cache off or nothing
        finished)."""
        with self._lock:
            return (self._prefix_hit_tokens / self._prompt_tokens
                    if self._prompt_tokens else 0.0)

    def report(self) -> Dict:
        """The ``slo`` section serving_bench / slo_report emit."""
        g = self.goodput()
        with self._lock:
            window_n = len(self._window)
            burn = self._burn_locked()
            hit = (self._prefix_hit_tokens / self._prompt_tokens
                   if self._prompt_tokens else 0.0)
        return {"targets": self._targets.to_dict(), "goodput": g,
                "burn_rate": round(burn, 6), "window_requests": window_n,
                "prefix_hit_ratio": round(hit, 6)}

    def admission_hint(self) -> Dict:
        """THE read hook for SLO-aware admission: live burn rate +
        goodput + declared targets.  Consumed once per engine step by
        inference/admission.py's ``slo_aware`` policy (slack ordering +
        shed threshold); the ``fifo`` default never calls it.  Changing
        its shape changes shedding behavior — it is load-bearing."""
        g = self.goodput()
        return {"burn_rate": self.burn_rate(),
                "request_goodput": g["request_goodput"],
                "token_goodput": g["token_goodput"],
                "prefix_hit_ratio": self.prefix_hit_ratio(),
                "targets": self._targets.to_dict()}


_SLO: Optional[SLOTracker] = None
_SLO_LOCK = threading.Lock()


def slo_tracker() -> SLOTracker:
    """The process SLO tracker (lazy singleton; targets resolved from
    the FLAGS_slo_* defaults until configure() overrides them)."""
    global _SLO
    if _SLO is None:
        with _SLO_LOCK:
            if _SLO is None:
                _SLO = SLOTracker()
    return _SLO


def reset_slo():
    """Re-resolve targets from flags and zero the accounting (tests /
    fresh measurement windows)."""
    slo_tracker().configure()
