"""Atomic file writes for checkpoint/save paths.

Every persistent artifact this framework writes (``io.py`` .npy/.npz
groups, the PS server's table snapshots, the sharded fleet checkpoints
in ``checkpoint.py``) goes through these helpers: the bytes land in a
unique temp name in the destination directory, are fsync'd, and then
``os.replace`` publishes them — so a reader can never observe a
half-written file, and a crash mid-save leaves the previous version
intact (reference invariant: fleet/collective's tmp-dir-then-mv epoch
checkpoints, generalized down to every individual file).
"""
from __future__ import annotations

import io as _io
import os
import zlib


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync so the rename itself is durable."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> int:
    """Write ``data`` to ``path`` atomically (tmp + fsync + os.replace).
    Returns the crc32 of the written bytes."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d)
    return zlib.crc32(data)


def atomic_savez(path: str, **arrays) -> int:
    """np.savez with atomic publication.  ``path`` gains ``.npz`` when
    missing (np.savez's own rule, applied to the FINAL name so the temp
    file and the published file agree).  Returns the crc32."""
    import numpy as np

    if not path.endswith(".npz"):
        path = path + ".npz"
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    return atomic_write_bytes(path, buf.getvalue())


def atomic_save_npy(path: str, arr) -> int:
    """np.save with atomic publication (``.npy`` appended when missing,
    matching np.save).  Returns the crc32."""
    import numpy as np

    if not path.endswith(".npy"):
        path = path + ".npy"
    buf = _io.BytesIO()
    np.save(buf, np.asarray(arr))
    return atomic_write_bytes(path, buf.getvalue())


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)
