"""contrib layer wrappers (reference: fluid/contrib/layers/nn.py)."""
from __future__ import annotations

from ...framework.dtype import VarType
from ...layer_helper import LayerHelper


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None, x_len=None, y_len=None):
    """reference: contrib/layers/nn.py:223 — X * W * Y var-length match
    matrix; padded [B,TL,D]/[B,TR,D] + optional Length vars here."""
    helper = LayerHelper("match_matrix_tensor", name=name)
    d = int(x.shape[-1])
    w = helper.create_parameter(
        attr=param_attr, shape=[d, channel_num * d], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    tmp = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x], "Y": [y], "W": [w]}
    if x_len is not None:
        inputs["LengthX"] = [x_len]
    if y_len is not None:
        inputs["LengthY"] = [y_len]
    helper.append_op("match_matrix_tensor", inputs=inputs,
                     outputs={"Out": [out], "Tmp": [tmp]},
                     attrs={"dim_t": channel_num})
    if act is not None:
        from ... import layers

        out = getattr(layers, act)(out)
    return out, tmp


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """reference: contrib/layers/nn.py sequence_topk_avg_pooling."""
    helper = LayerHelper("sequence_topk_avg_pooling")
    out = helper.create_variable_for_type_inference(input.dtype)
    pos = helper.create_variable_for_type_inference(VarType.INT32)
    helper.append_op("sequence_topk_avg_pooling",
                     inputs={"X": [input], "ROW": [row], "COLUMN": [col]},
                     outputs={"Out": [out], "pos": [pos]},
                     attrs={"topks": list(topks),
                            "channel_num": channel_num})
    return out


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32"):
    """reference: contrib/layers/nn.py tdm_child — TreeInfo is a
    [node_nums, 3 + child_nums] int parameter."""
    helper = LayerHelper("tdm_child")
    tree_info = helper.create_parameter(
        attr=param_attr, shape=[node_nums, 3 + child_nums], dtype=dtype)
    child = helper.create_variable_for_type_inference(VarType.INT64)
    mask = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("tdm_child", inputs={"X": [x],
                                          "TreeInfo": [tree_info]},
                     outputs={"Child": [child], "LeafMask": [mask]},
                     attrs={"child_nums": child_nums})
    return child, mask


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list, leaf_node_num,
                tree_travel_attr=None, tree_layer_attr=None,
                output_positive=True, output_list=True, seed=0,
                tree_dtype="int32", dtype="int32"):
    """reference: contrib/layers/nn.py tdm_sampler."""
    helper = LayerHelper("tdm_sampler")
    layer_nums = len(neg_samples_num_list)
    offsets, acc = [0], 0
    for n in layer_node_num_list:
        acc += int(n)
        offsets.append(acc)
    travel = helper.create_parameter(
        attr=tree_travel_attr, shape=[leaf_node_num, layer_nums],
        dtype=tree_dtype)
    layer = helper.create_parameter(
        attr=tree_layer_attr, shape=[acc, 1], dtype=tree_dtype)
    out = helper.create_variable_for_type_inference(VarType.INT64)
    labels = helper.create_variable_for_type_inference(VarType.INT64)
    mask = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op(
        "tdm_sampler",
        inputs={"X": [x], "Travel": [travel], "Layer": [layer]},
        outputs={"Out": [out], "Labels": [labels], "Mask": [mask]},
        attrs={"neg_samples_num_list": list(neg_samples_num_list),
               "layer_offset_lod": offsets,
               "output_positive": output_positive, "seed": seed})
    return out, labels, mask


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    """reference: contrib/layers/nn.py multiclass_nms2."""
    helper = LayerHelper("multiclass_nms2", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    index = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op(
        "multiclass_nms2",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "Index": [index]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    if return_index:
        return out, index
    return out
