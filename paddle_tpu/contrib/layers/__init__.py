"""fluid.contrib.layers (reference: python/paddle/fluid/contrib/layers/
nn.py __all__) — the ops themselves live in ops/parity_ops.py and
ops/long_tail_ops.py; this module is the python surface."""
from .nn import (  # noqa: F401
    match_matrix_tensor,
    multiclass_nms2,
    sequence_topk_avg_pooling,
    tdm_child,
    tdm_sampler,
)
