from . import mixed_precision
