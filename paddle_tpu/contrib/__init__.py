from . import mixed_precision
from . import slim
from . import layers
