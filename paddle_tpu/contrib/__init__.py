from . import mixed_precision
from . import slim
