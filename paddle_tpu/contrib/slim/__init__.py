"""Slim: quantization-aware training + post-training quantization.

Reference: python/paddle/fluid/contrib/slim/ (quantization passes over
IrGraph; here the passes rewrite the Program directly — the TPU build's
program IR is already the mutable graph).
"""
from . import quantization  # noqa: F401
from .quantization import (  # noqa: F401
    OutScaleForTrainingPass,
    PostTrainingQuantization,
    QuantizationFreezePass,
    QuantizationTransformPass,
)
