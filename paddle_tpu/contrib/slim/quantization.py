"""Quantization program-rewrite passes.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py — QuantizationTransformPass:152 (insert fake
quant/dequant on the inputs of quantizable ops), OutScaleForTrainingPass,
QuantizationFreezePass; post_training_quantization.py.

The reference operates on IrGraph (C++ ir::Graph binding); here the
Program IR is Python-native, so the passes edit blocks in place.
bf16 stays the training compute dtype — fake quant ops simulate int8
on the MXU-friendly path and real int8 materialization happens at
freeze time.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ...framework.core import Program
from ...framework import unique_name

QUANTIZABLE_DEFAULT = ["conv2d", "depthwise_conv2d", "mul", "matmul",
                       "matmul_v2"]
# input slots that carry weights for each quantizable type
_WEIGHT_SLOTS = {
    "conv2d": "Filter",
    "depthwise_conv2d": "Filter",
    "mul": "Y",
    "matmul": "Y",
    "matmul_v2": "Y",
}
_ACT_SLOTS = {
    "conv2d": "Input",
    "depthwise_conv2d": "Input",
    "mul": "X",
    "matmul": "X",
    "matmul_v2": "X",
}


def _is_param(block, name):
    v = block._find_var_recursive(name)
    return v is not None and getattr(v, "persistable", False) and \
        type(v).__name__ == "Parameter"


class QuantizationTransformPass:
    """Insert fake quant-dequant before quantizable ops (QAT).

    reference: quantization_pass.py:152 QuantizationTransformPass."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 moving_rate=0.9, skip_pattern="skip_quant",
                 quantizable_op_type=None, is_test=False):
        self._weight_bits = weight_bits
        self._act_bits = activation_bits
        self._act_type = activation_quantize_type
        self._weight_type = weight_quantize_type
        self._moving_rate = moving_rate
        self._skip_pattern = skip_pattern
        self._types = list(quantizable_op_type or QUANTIZABLE_DEFAULT)
        self._is_test = is_test
        self.quanted_activations: Dict[str, str] = {}  # var -> scale var
        self._qmap: Dict[str, str] = {}   # raw var -> quantized var
        self._qdq_op_ids = set()

    def apply(self, program: Program, startup_program: Optional[Program] = None):
        block = program.global_block()
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in self._types or \
                    op.attrs.get(self._skip_pattern, False):
                i += 1
                continue
            inserted = 0
            wslot = _WEIGHT_SLOTS.get(op.type)
            aslot = _ACT_SLOTS.get(op.type)
            for slot in list(op.inputs):
                for k, name in enumerate(op.inputs[slot]):
                    is_w = slot == wslot and _is_param(block, name)
                    if not (is_w or slot == aslot):
                        continue
                    if name in self._qmap:  # shared var: reuse one qdq op
                        op.inputs[slot][k] = self._qmap[name]
                        continue
                    qname, n_ops = self._insert_qdq(
                        block, i, name, is_weight=is_w,
                        startup_program=startup_program)
                    self._qmap[name] = qname
                    op.inputs[slot][k] = qname
                    inserted += n_ops
            op._set_attr("quantization_type", "qat_with_weight")
            i += 1 + inserted
        self._rewire_other_consumers(block)
        return program

    def _rewire_other_consumers(self, block):
        """Point every other reader of a quantized var (grad ops above
        all — the STE path must reach the backward) at the quantized
        tensor.  Ops that *write* the raw var (optimizer updates of the
        fp master weight) and the fake-quant ops themselves keep the raw
        name — mirrors the reference IrGraph pass rewiring all uses
        (quantization_pass.py dequantized_vars)."""
        available = set()  # quantized vars defined so far in op order
        for op in block.ops:
            if id(op) in self._qdq_op_ids:
                for ns in op.outputs.values():
                    available.update(ns)
                continue
            writes = {n for ns in op.outputs.values() for n in ns}
            for slot, names in op.inputs.items():
                for k, name in enumerate(names):
                    qname = self._qmap.get(name)
                    if qname is None or qname not in available or \
                            name in writes or names[k] == qname:
                        continue
                    op.inputs[slot][k] = qname
        block.program._bump_version()

    def _insert_qdq(self, block, index, name, is_weight, startup_program):
        src = block._find_var_recursive(name)
        qvar = block.create_var(
            name=unique_name.generate(f"{name}.quantized"),
            shape=src.shape, dtype=src.dtype, stop_gradient=False)
        if is_weight:
            if self._weight_type == "channel_wise_abs_max":
                op_type = "fake_channel_wise_quantize_dequantize_abs_max"
                axis = 1 if len(src.shape) == 2 else 0
                n_scales = src.shape[axis]
            else:
                op_type = "fake_quantize_dequantize_abs_max"
                axis, n_scales = -1, 1
            scale = block.create_var(
                name=unique_name.generate(f"{name}.scale"),
                shape=[n_scales], dtype="float32", stop_gradient=True)
            qop = block._insert_op(
                index, op_type, inputs={"X": [name]},
                outputs={"Out": [qvar.name], "OutScale": [scale.name]},
                attrs={"bit_length": self._weight_bits, "quant_axis": axis})
            self._qdq_op_ids.add(id(qop))
            return qvar.name, 1
        # activation: EMA scale threading through a persistable state var
        scale = block.create_var(
            name=unique_name.generate(f"{name}.quant_scale"),
            shape=[1], dtype="float32", persistable=True, stop_gradient=True)
        if startup_program is not None:
            sb = startup_program.global_block()
            sb.create_var(name=scale.name, shape=[1], dtype="float32",
                          persistable=True, stop_gradient=True)
            sb.append_op("fill_constant", outputs={"Out": [scale.name]},
                         attrs={"shape": [1], "dtype": "float32",
                                "value": 0.0})
        qop = block._insert_op(
            index, "fake_quantize_moving_average_abs_max",
            inputs={"X": [name], "InScale": [scale.name]},
            outputs={"Out": [qvar.name], "OutScale": [scale.name]},
            attrs={"bit_length": self._act_bits,
                   "moving_rate": self._moving_rate,
                   "is_test": self._is_test})
        self._qdq_op_ids.add(id(qop))
        self.quanted_activations[name] = scale.name
        return qvar.name, 1


class OutScaleForTrainingPass:
    """Track output scales of quantizable-adjacent ops for later export.

    reference: quantization_pass.py OutScaleForTrainingPass."""

    _OUT_SLOT = {"conv2d": "Output", "depthwise_conv2d": "Output",
                 "mul": "Out", "matmul": "Out", "matmul_v2": "Out",
                 "relu": "Out", "batch_norm": "Y"}

    def __init__(self, scope=None, place=None, moving_rate=0.9,
                 op_types=None):
        self._moving_rate = moving_rate
        self._types = list(op_types or self._OUT_SLOT)
        self.scales: Dict[str, str] = {}

    def apply(self, program: Program, startup_program: Optional[Program] = None):
        block = program.global_block()
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            slot = self._OUT_SLOT.get(op.type)
            if op.type not in self._types or slot is None or \
                    not op.outputs.get(slot):
                i += 1
                continue
            out_name = op.outputs[slot][0]
            if out_name in self.scales:
                i += 1
                continue
            scale = block.create_var(
                name=unique_name.generate(f"{out_name}.out_scale"),
                shape=[1], dtype="float32", persistable=True,
                stop_gradient=True)
            if startup_program is not None:
                sb = startup_program.global_block()
                sb.create_var(name=scale.name, shape=[1], dtype="float32",
                              persistable=True, stop_gradient=True)
                sb.append_op("fill_constant", outputs={"Out": [scale.name]},
                             attrs={"shape": [1], "dtype": "float32",
                                    "value": 0.0})
            block._insert_op(
                i + 1, "moving_average_abs_max_scale",
                inputs={"X": [out_name], "InScale": [scale.name]},
                outputs={"OutScale": [scale.name]},
                attrs={"moving_rate": self._moving_rate})
            self.scales[out_name] = scale.name
            i += 2
        return program


class QuantizationFreezePass:
    """Freeze a QAT program for deployment: weights are round-tripped
    through int8 once on host (so deploy numerics == int8 numerics while
    XLA still computes in bf16/f32), and the activation fake-quant ops
    switch to is_test (fixed EMA scales).  Real int8 storage for export
    uses the quantize_linear/dequantize_linear ops.

    reference: quantization_pass.py QuantizationFreezePass."""

    def __init__(self, scope, place=None, weight_bits=8, activation_bits=8):
        self._scope = scope
        self._weight_bits = weight_bits

    def apply(self, program: Program):
        block = program.global_block()
        qmax = float(2 ** (self._weight_bits - 1) - 1)
        for op in list(block.ops):
            if op.type in ("fake_quantize_dequantize_abs_max",
                           "fake_channel_wise_quantize_dequantize_abs_max"):
                wname = op.inputs["X"][0]
                w = self._scope.find_var(wname)
                if w is None or w.get() is None:
                    continue
                val = np.asarray(w.get())
                axis = int(op.attrs.get("quant_axis", -1))
                if op.type.startswith("fake_channel"):
                    red = tuple(i for i in range(val.ndim) if i != axis)
                    scale = np.abs(val).max(axis=red, keepdims=True)
                else:
                    scale = np.asarray(np.abs(val).max()).reshape(1)
                scale = np.maximum(scale, 1e-9)
                q = np.clip(np.round(val / scale * qmax), -qmax - 1, qmax)
                # store the dequantized-from-int8 weights back: deploy
                # numerics == int8 numerics while XLA still sees bf16/f32
                w.set((q * scale / qmax).astype(val.dtype))
                op._set_attr("__frozen__", True)
            elif op.type == "fake_quantize_moving_average_abs_max":
                op._set_attr("is_test", True)
        return program


class PostTrainingQuantization:
    """Calibrate activation scales on sample batches, then emit a program
    with fixed-scale quant-dequant (abs_max algo; 'hist' keeps a
    percentile of the abs distribution).

    reference: post_training_quantization.py PostTrainingQuantization."""

    def __init__(self, executor, program, feed_list: Sequence[str],
                 data_loader, batch_nums=4, algo="abs_max",
                 hist_percent=0.9999, quantizable_op_type=None,
                 weight_bits=8, activation_bits=8, scope=None):
        self._exe = executor
        self._program = program
        self._feeds = list(feed_list)
        self._loader = data_loader
        self._batch_nums = batch_nums
        self._algo = algo
        self._hist_percent = hist_percent
        self._types = list(quantizable_op_type or QUANTIZABLE_DEFAULT)
        self._weight_bits = weight_bits
        self._act_bits = activation_bits
        self._scope = scope

    def quantize(self) -> Program:
        block = self._program.global_block()
        # vars to observe: activation inputs of quantizable ops
        observe = []
        for op in block.ops:
            if op.type in self._types:
                aslot = _ACT_SLOTS.get(op.type)
                if aslot and op.inputs.get(aslot):
                    name = op.inputs[aslot][0]
                    if not _is_param(block, name) and name not in observe:
                        observe.append(name)
        # calibration runs
        samples: Dict[str, List[float]] = {n: [] for n in observe}
        for bi, feed in enumerate(self._loader()):
            if bi >= self._batch_nums:
                break
            missing = set(self._feeds) - set(feed)
            if missing:
                raise ValueError(
                    f"calibration batch {bi} is missing feeds {missing} "
                    f"declared in feed_list")
            vals = self._exe.run(self._program, feed=feed,
                                 fetch_list=observe, scope=self._scope)
            for name, v in zip(observe, vals):
                arr = np.abs(np.asarray(v)).ravel()
                if self._algo == "hist":
                    samples[name].append(
                        float(np.quantile(arr, self._hist_percent)))
                else:
                    samples[name].append(float(arr.max()))
        scales = {n: max(v) if v else 1.0 for n, v in samples.items()}

        # rewrite: insert fixed-scale qdq on activations + weight qdq
        quant_prog = self._program.clone()
        qblock = quant_prog.global_block()
        i = 0
        while i < len(qblock.ops):
            op = qblock.ops[i]
            if op.type not in self._types:
                i += 1
                continue
            inserted = 0
            aslot = _ACT_SLOTS.get(op.type)
            wslot = _WEIGHT_SLOTS.get(op.type)
            if aslot and op.inputs.get(aslot):
                name = op.inputs[aslot][0]
                if name in scales:
                    src = qblock._find_var_recursive(name)
                    qv = qblock.create_var(
                        name=unique_name.generate(f"{name}.ptq"),
                        shape=src.shape, dtype=src.dtype)
                    sv = qblock.create_var(
                        name=unique_name.generate(f"{name}.ptq_scale"),
                        shape=[1], dtype="float32", stop_gradient=True)
                    qblock._insert_op(
                        i, "assign_value", outputs={"Out": [sv.name]},
                        attrs={"shape": [1], "dtype": "float32",
                               "fp32_values": [scales[name]]})
                    qblock._insert_op(
                        i + 1, "fake_quantize_moving_average_abs_max",
                        inputs={"X": [name], "InScale": [sv.name]},
                        outputs={"Out": [qv.name]},
                        attrs={"bit_length": self._act_bits,
                               "is_test": True})
                    op.inputs[aslot][0] = qv.name
                    inserted += 2
            if wslot and op.inputs.get(wslot):
                name = op.inputs[wslot][0]
                if _is_param(qblock, name):
                    src = qblock._find_var_recursive(name)
                    qv = qblock.create_var(
                        name=unique_name.generate(f"{name}.ptq"),
                        shape=src.shape, dtype=src.dtype)
                    sv = qblock.create_var(
                        name=unique_name.generate(f"{name}.ptq_scale"),
                        shape=[src.shape[1] if len(src.shape) == 2
                               else src.shape[0]],
                        dtype="float32", stop_gradient=True)
                    axis = 1 if len(src.shape) == 2 else 0
                    qblock._insert_op(
                        i + inserted,
                        "fake_channel_wise_quantize_dequantize_abs_max",
                        inputs={"X": [name]},
                        outputs={"Out": [qv.name], "OutScale": [sv.name]},
                        attrs={"bit_length": self._weight_bits,
                               "quant_axis": axis})
                    op.inputs[wslot][0] = qv.name
                    inserted += 1
            op.attrs["quantization_type"] = "post_training"
            i += 1 + inserted
        return quant_prog
