from .decorator import decorate, OptimizerWithMixedPrecision
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program, cast_model_to_fp16
