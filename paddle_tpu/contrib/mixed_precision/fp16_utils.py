"""AMP program rewrite: insert casts around white/black-list ops.

Reference: fluid/contrib/mixed_precision/fp16_utils.py:190
rewrite_program — walks the forward program, casting inputs of white-list
ops to the low dtype and inputs of black-list ops back to fp32.  Backward
needs no separate handling here: grad ops vjp-replay the forward
lowerings *including the inserted casts*, so parameter gradients come out
fp32 (master weights) automatically.
"""
from __future__ import annotations

from typing import Dict

from ...framework import unique_name
from ...framework.core import Block, Program
from ...framework.dtype import VarType


def _insert_cast(block: Block, idx: int, in_name: str, dst_dtype: VarType,
                 cache: Dict) -> str:
    key = (in_name, int(dst_dtype))
    if key in cache:
        return cache[key][0]
    src_var = block._find_var_recursive(in_name)
    out_name = unique_name.generate(f"{in_name}.cast_{'bf16' if dst_dtype == VarType.BF16 else dst_dtype}")
    block.create_var(name=out_name, shape=src_var.shape, dtype=dst_dtype)
    block._insert_op(
        idx, "cast",
        inputs={"X": [in_name]}, outputs={"Out": [out_name]},
        attrs={"in_dtype": int(src_var.dtype), "out_dtype": int(dst_dtype)},
    )
    cache[key] = (out_name, idx)
    return out_name


def rewrite_program(main_program: Program, amp_lists, dest_dtype=VarType.BF16):
    """Cast-insertion pass over the (forward) program."""
    block = main_program.global_block()
    i = 0
    cache: Dict = {}
    low_vars = set()  # vars known to be in low precision
    while i < len(block.ops):
        op_ = block.ops[i]
        if op_.type == "cast":
            i += 1
            continue
        if op_.type in amp_lists.white_list:
            num_inserted = 0
            for slot, names in list(op_.inputs.items()):
                new_names = []
                for n in names:
                    var = block._find_var_recursive(n)
                    if (var is not None and var.dtype == VarType.FP32
                            and n not in amp_lists.black_varnames):
                        casted = _insert_cast(block, i, n, dest_dtype, cache)
                        new_names.append(casted)
                        num_inserted += 1 if casted != n else 0
                    else:
                        new_names.append(n)
                op_.inputs[slot] = new_names
            # re-locate op after insertions
            i = block.ops.index(op_)
            for names in op_.outputs.values():
                for n in names:
                    var = block._find_var_recursive(n)
                    if var is not None and var.dtype == VarType.FP32:
                        var.dtype = dest_dtype
                        low_vars.add(n)
        elif op_.type in amp_lists.black_list:
            for slot, names in list(op_.inputs.items()):
                new_names = []
                for n in names:
                    var = block._find_var_recursive(n)
                    if var is not None and var.dtype == dest_dtype:
                        casted = _insert_cast(block, i, n, VarType.FP32, cache)
                        new_names.append(casted)
                    else:
                        new_names.append(n)
                op_.inputs[slot] = new_names
            i = block.ops.index(op_)
        i += 1
    main_program._bump_version()
    return main_program


def cast_model_to_fp16(program, amp_lists=None, dest_dtype=VarType.BF16):
    """Pure-low-precision conversion (reference: fp16_utils.py
    cast_model_to_fp16) — used by inference export."""
    from .fp16_lists import AutoMixedPrecisionLists

    return rewrite_program(program, amp_lists or AutoMixedPrecisionLists(),
                           dest_dtype)
