"""AMP op lists (reference: fluid/contrib/mixed_precision/fp16_lists.py).

TPU-first: the low-precision dtype is bfloat16 (same exponent range as
fp32 — no loss scaling needed), fp16 is available for parity.
"""
from __future__ import annotations

white_list = {
    "conv2d",
    "depthwise_conv2d",
    "conv3d",
    "conv2d_transpose",
    "matmul",
    "matmul_v2",
    "mul",
    "bmm",
}

black_list = {
    "exp",
    "square",
    "log",
    "mean",
    "sum",
    "cos_sim",
    "softmax",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "cross_entropy",
    "cross_entropy2",
}

# ops that run in whichever precision their inputs arrive in
gray_list = {
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow",
    "batch_norm", "layer_norm", "tanh", "sigmoid", "lookup_table",
    "lookup_table_v2", "relu", "relu6", "leaky_relu", "gelu", "swish",
    "top_k", "pool2d", "dropout", "reshape2", "transpose2", "concat", "split",
    "slice", "stack", "unstack", "squeeze2", "unsqueeze2", "flatten2",
    "flatten_contiguous_range", "scale", "expand", "gather", "pad", "pad2d",
    "reduce_mean", "reduce_sum",
}


class AutoMixedPrecisionLists:
    """reference: fp16_lists.py AutoMixedPrecisionLists."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.gray_list = set(gray_list)
        self.black_varnames = set(custom_black_varnames or [])
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
