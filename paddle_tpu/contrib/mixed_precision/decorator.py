"""AMP optimizer decorator.

Reference: fluid/contrib/mixed_precision/decorator.py:27
OptimizerWithMixedPrecision / :218 decorate — wraps an optimizer so
minimize() rewrites the program to mixed precision and (for fp16) applies
dynamic loss scaling (:333).  TPU-first: the default low dtype is bf16,
whose exponent range equals fp32, so loss scaling defaults OFF; the
dynamic-loss-scaling machinery (isfinite check + scale update) is
implemented for fp16 parity.
"""
from __future__ import annotations

from ...framework.core import default_main_program
from ...framework.dtype import VarType
from ...layers import nn as nn_layers
from ...layers import tensor as tensor_layers
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 dest_dtype=VarType.BF16):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = init_loss_scaling
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._dest_dtype = dest_dtype
        self._scaled_loss = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def get_scaled_loss(self):
        return self._scaled_loss

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        program = loss.block.program
        rewrite_program(program, self._amp_lists, self._dest_dtype)
        needs_scaling = (self._dest_dtype == VarType.FP16
                         and self._loss_scaling != 1.0)
        if needs_scaling:
            self._scaled_loss = nn_layers.scale(loss, self._loss_scaling)
        else:
            self._scaled_loss = loss
        params_grads = self._optimizer.backward(
            self._scaled_loss, startup_program, parameter_list, no_grad_set,
            callbacks)
        if needs_scaling:
            inv = 1.0 / self._loss_scaling
            params_grads = [
                (p, nn_layers.scale(g, inv) if g is not None else g)
                for p, g in params_grads
            ]
        return params_grads

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_optimize(loss, startup_program,
                                              params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self._optimizer.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_fp16=False):
    """reference: decorator.py:218 decorate.  Default dtype is bf16 (no
    loss scaling); pass use_fp16=True for reference-exact fp16 semantics."""
    dest = VarType.FP16 if use_fp16 else VarType.BF16
    if dest == VarType.BF16:
        init_loss_scaling = 1.0
        use_dynamic_loss_scaling = False
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        dest,
    )
