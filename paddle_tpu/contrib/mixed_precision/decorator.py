"""AMP optimizer decorator.

Reference: fluid/contrib/mixed_precision/decorator.py:27
OptimizerWithMixedPrecision / :218 decorate — wraps an optimizer so
minimize() rewrites the program to mixed precision and (for fp16) applies
dynamic loss scaling (:333).  TPU-first: the default low dtype is bf16,
whose exponent range equals fp32, so loss scaling defaults OFF; the
dynamic-loss-scaling machinery is the reference-shaped in-program state
machine — loss scaled by a persistable ``loss_scaling`` var,
``amp_check_finite_and_scale`` unscales the grads (zeroing them on a
found-Inf step) and ``update_loss_scaling`` walks the scale/counter
state (ops/extra_ops.py).

Observability (r20): the found_inf flag and the live scale are
persistable program state, so the numerics probe stream
(framework/numerics.py, ``FLAGS_numerics_probe=1``) picks them up by op
type and emits ``amp_found_inf_total`` / ``amp_loss_scale`` telemetry,
annotates the current span on found-Inf steps, and feeds the
HealthMonitor — a silent run of skipped updates is now a visible one.
"""
from __future__ import annotations

from ...backward import OP_ROLE_KEY, OpRole
from ...framework import unique_name
from ...framework.core import default_main_program
from ...framework.dtype import VarType
from ...layers import nn as nn_layers
from ...layers import tensor as tensor_layers
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 dest_dtype=VarType.BF16):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = init_loss_scaling
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dest_dtype = dest_dtype
        self._scaled_loss = None
        self._loss_scaling_var = None
        self._found_inf_var = None
        self._good_steps_var = None
        self._bad_steps_var = None

    def get_loss_scaling(self):
        """The python-side init value; under dynamic scaling the LIVE
        scale is the persistable var (``get_loss_scaling_var``)."""
        return self._loss_scaling

    def get_loss_scaling_var(self):
        return self._loss_scaling_var

    def get_found_inf_var(self):
        return self._found_inf_var

    def get_scaled_loss(self):
        return self._scaled_loss

    # ------------------------------------------------------------------
    def _dynamic(self) -> bool:
        return (self._dest_dtype == VarType.FP16
                and self._use_dynamic_loss_scaling)

    def _init_scaling_state(self):
        if self._loss_scaling_var is not None:
            return
        self._loss_scaling_var = tensor_layers.create_global_var(
            shape=[1], value=float(self._loss_scaling), dtype="float32",
            persistable=True, name=unique_name.generate("loss_scaling"))
        self._good_steps_var = tensor_layers.create_global_var(
            shape=[1], value=0, dtype="int32", persistable=True,
            name=unique_name.generate("loss_scaling_good_steps"))
        self._bad_steps_var = tensor_layers.create_global_var(
            shape=[1], value=0, dtype="int32", persistable=True,
            name=unique_name.generate("loss_scaling_bad_steps"))

    def _append_dynamic_unscale(self, block, params_grads):
        """After backward: unscale every grad by the live 1/scale
        (zeroing them all when any is non-finite) and step the
        loss-scaling state machine — all in-program, so the executor,
        checkpointing and the numerics probes see it as ordinary
        persistable state."""
        grads = [g.name for _, g in params_grads if g is not None]
        if not grads:
            return
        scale = self._loss_scaling_var.name
        inv = unique_name.generate("loss_scaling_inv")
        block.create_var(name=inv, shape=[1], dtype=VarType.FP32)
        block.append_op("reciprocal", inputs={"X": [scale]},
                        outputs={"Out": [inv]},
                        attrs={OP_ROLE_KEY: int(OpRole.Backward)})
        found = unique_name.generate("found_infinite")
        self._found_inf_var = block.create_var(
            name=found, shape=[1], dtype=VarType.BOOL, persistable=True)
        block.append_op(
            "amp_check_finite_and_scale",
            inputs={"X": list(grads), "Scale": [inv]},
            outputs={"Out": list(grads), "FoundInfinite": [found]},
            attrs={OP_ROLE_KEY: int(OpRole.Backward)})
        good, bad = self._good_steps_var.name, self._bad_steps_var.name
        block.append_op(
            "update_loss_scaling",
            inputs={"FoundInfinite": [found], "PrevLossScaling": [scale],
                    "InGoodSteps": [good], "InBadSteps": [bad]},
            outputs={"LossScalingOut": [scale], "OutGoodSteps": [good],
                     "OutBadSteps": [bad]},
            attrs={"incr_every_n_steps": int(self._incr_every_n_steps),
                   "decr_every_n_nan_or_inf":
                       int(self._decr_every_n_nan_or_inf),
                   "incr_ratio": float(self._incr_ratio),
                   "decr_ratio": float(self._decr_ratio),
                   OP_ROLE_KEY: int(OpRole.Optimize)})

    # ------------------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        program = loss.block.program
        rewrite_program(program, self._amp_lists, self._dest_dtype)
        if self._dynamic():
            self._init_scaling_state()
            self._scaled_loss = nn_layers.elementwise_mul(
                loss, self._loss_scaling_var)
            params_grads = self._optimizer.backward(
                self._scaled_loss, startup_program, parameter_list,
                no_grad_set, callbacks)
            self._append_dynamic_unscale(loss.block, params_grads)
            return params_grads
        needs_scaling = (self._dest_dtype == VarType.FP16
                         and self._loss_scaling != 1.0)
        if needs_scaling:
            self._scaled_loss = nn_layers.scale(loss, self._loss_scaling)
        else:
            self._scaled_loss = loss
        params_grads = self._optimizer.backward(
            self._scaled_loss, startup_program, parameter_list, no_grad_set,
            callbacks)
        if needs_scaling:
            inv = 1.0 / self._loss_scaling
            params_grads = [
                (p, nn_layers.scale(g, inv) if g is not None else g)
                for p, g in params_grads
            ]
        return params_grads

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_optimize(loss, startup_program,
                                              params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self._optimizer.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_fp16=False):
    """reference: decorator.py:218 decorate.  Default dtype is bf16 (no
    loss scaling); pass use_fp16=True for reference-exact fp16 semantics
    including the dynamic loss-scaling state machine."""
    dest = VarType.FP16 if use_fp16 else VarType.BF16
    if dest == VarType.BF16:
        init_loss_scaling = 1.0
        use_dynamic_loss_scaling = False
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        dest,
    )
