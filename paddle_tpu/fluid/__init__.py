"""The `fluid` namespace — API-compatible surface with the reference's
python/paddle/fluid package, assembled from the TPU-native implementation.

A reference-era script should run with `import paddle_tpu.fluid as fluid`
and a Place swap (the north star in BASELINE.json).
"""
from ..framework.core import (
    Program,
    Variable,
    Operator,
    Block,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
    in_dygraph_mode,
)
from ..framework.place import (
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    TPUPinnedPlace,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from ..framework.scope import Scope, LoDTensor, global_scope, scope_guard
from ..framework.dtype import VarType
from ..framework import unique_name
from ..executor import Executor
from ..backward import append_backward, gradients
from ..param_attr import ParamAttr, WeightNormParamAttr
from .. import initializer
from .. import layers
from .. import metrics
from .. import optimizer
from .. import regularizer
from .. import clip
from ..clip import (
    GradientClipByGlobalNorm,
    GradientClipByNorm,
    GradientClipByValue,
)
from ..initializer import set_global_initializer
from .. import dygraph
from ..dygraph.base import enable_dygraph, disable_dygraph
from ..parallel.compiled_program import (
    BuildStrategy,
    CompiledProgram,
    ExecutionStrategy,
)
from .. import io
from ..io import (
    save,
    load,
    save_params,
    load_params,
    save_persistables,
    load_persistables,
    save_inference_model,
    load_inference_model,
)
from .. import backward
from .. import nets
from ..reader import DataFeeder
from .. import reader
from .. import data_feed as dataset
from ..data_feed import (
    DatasetFactory,
    InMemoryDataset,
    QueueDataset,
    DataFeedDesc,
)

# framework module alias (scripts do fluid.framework.xxx)
from .. import framework
from .. import contrib

# data layers at fluid level (fluid.data = shape-verbatim variant)
def data(name, shape, dtype="float32", lod_level=0):
    return layers.data(name, shape, dtype=dtype, lod_level=lod_level,
                       append_batch_size=False)


embedding = layers.embedding
one_hot = layers.one_hot


class core:
    """Placeholder for reference's `fluid.core` pybind module: common
    attributes scripts touch."""

    VarDesc = None
    from ..framework.scope import LoDTensor, Scope
    from ..framework.place import CPUPlace, CUDAPlace, TPUPlace

    @staticmethod
    def get_tpu_device_count():
        import jax

        try:
            devs = [d for d in jax.devices() if d.platform != "cpu"]
            return len(devs)
        except Exception:
            return 0

    get_cuda_device_count = get_tpu_device_count


def cuda_places(device_ids=None):
    n = core.get_tpu_device_count()
    if device_ids is None:
        device_ids = list(range(max(n, 1)))
    return [TPUPlace(i) for i in device_ids]


tpu_places = cuda_places


def cpu_places(device_count=None):
    import os

    if device_count is None:
        device_count = int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(device_count)]


from ..framework.core import device_guard  # noqa: F401


_flags = {}


def set_flags(d):
    """reference: framework.py:5480 fluid.set_flags (gflags bridge)."""
    from ..utils import flags as flag_mod

    flag_mod.set_flags(d)


def get_flags(keys):
    from ..utils import flags as flag_mod

    return flag_mod.get_flags(keys)


from .. import profiler  # noqa: F401  (reference: fluid/profiler.py)
from .. import inference  # noqa: F401  (reference: fluid.core inference api)
from ..inference import (  # noqa: F401
    AnalysisConfig,
    AnalysisPredictor,
    PaddleTensor,
    create_paddle_predictor,
)


from ..utils.custom_op import load_op_library, register_op  # noqa: F401
