"""Checkpoint / model save-load.

Reference: python/paddle/fluid/io.py (save/load_vars:224/373,
save/load_params:598, save/load_persistables, save/load_inference_model
:1093/:1303, unified save/load :1598/:1662).  Storage is
host-side numpy (.npz per group or one file per var) + the Program's JSON
desc for inference models; sharded orbax-style checkpoints come with the
distributed phase.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .executor import as_numpy
from .framework.core import Parameter, Program, Variable, default_main_program
from .framework.dtype import to_numpy_dtype
from .framework.scope import global_scope
from .utils.atomic_io import (atomic_save_npy, atomic_savez,
                              atomic_write_bytes)

__all__ = [
    "save_vars", "load_vars", "save_params", "load_params",
    "save_persistables", "load_persistables", "save_inference_model",
    "load_inference_model", "save", "load", "get_program_persistable_vars",
]


def _is_persistable(var: Variable) -> bool:
    return bool(var.persistable) and var.type not in ()


def _is_parameter(var: Variable) -> bool:
    return isinstance(var, Parameter)


def get_program_persistable_vars(program: Program) -> List[Variable]:
    return [v for v in program.list_vars() if _is_persistable(v)]


def _gather(executor, program, predicate, vars=None):
    if vars is None:
        vars = [v for v in program.list_vars() if predicate(v)]
    scope = global_scope()
    out = {}
    for v in vars:
        val = scope.get(v.name)
        if val is None:
            raise RuntimeError(f"var {v.name!r} has no value in scope")
        out[v.name] = as_numpy(val)
    return out


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    """reference: io.py:224."""
    main_program = main_program or default_main_program()
    if vars is None:
        predicate = predicate or _is_persistable
        vars = [v for v in main_program.list_vars() if predicate(v)]
    data = _gather(executor, main_program, lambda v: True, vars)
    os.makedirs(dirname, exist_ok=True)
    # atomic per file (tmp + fsync + os.replace): a crash mid-save must
    # leave the previous checkpoint files intact, never a torn .npz
    # that load_persistables half-applies or crashes on
    if filename is not None:
        atomic_savez(os.path.join(dirname, filename), **data)
    else:
        for name, arr in data.items():
            atomic_save_npy(
                os.path.join(dirname, name.replace("/", "__") + ".npy"), arr)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    """reference: io.py:373.

    Known cross-framework incompatibility: pyramid_hash embeddings.
    This build hashes chunks with keyed blake2s where the reference
    uses XXH32 (ops/long_tail_ops.py pyramid_hash), so row indices into
    a pyramid-hash W differ — reference-trained pyramid_hash weights
    load byte-fine but look up DIFFERENT rows.  A warning fires below
    when such a param is loaded into a program containing the op."""
    main_program = main_program or default_main_program()
    if vars is None:
        predicate = predicate or _is_persistable
        vars = [v for v in main_program.list_vars() if predicate(v)]
    _warn_pyramid_hash_load(main_program, vars)
    scope = global_scope()
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not path.endswith(".npz"):
            path = path + ".npz"
        with np.load(path, allow_pickle=False) as z:
            for v in vars:
                if v.name in z:
                    scope.set(v.name, np.asarray(z[v.name]))
    else:
        for v in vars:
            path = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if os.path.exists(path):
                scope.set(v.name, np.load(path))
            else:
                raise RuntimeError(f"checkpoint file missing for var {v.name!r}: {path}")


def _warn_pyramid_hash_load(main_program, vars):
    """r5 (advisor): loading weights into a pyramid_hash W is silently
    incompatible with REFERENCE-trained checkpoints (blake2s vs XXH32
    row hashing) — warn once per load so from-scratch training stays
    quiet but checkpoint migration is flagged."""
    try:
        hash_ws = set()
        for block in main_program.blocks:
            for op_ in block.ops:
                if op_.type == "pyramid_hash":
                    hash_ws.update(op_.input("W") or [])
        loaded = hash_ws & {v.name for v in vars}
        if loaded:
            import warnings

            warnings.warn(
                f"loading pyramid_hash weight(s) {sorted(loaded)}: this "
                "build hashes with keyed blake2s, not the reference's "
                "XXH32 — weights trained by the reference index different "
                "rows here (fine for checkpoints produced by THIS "
                "framework)", RuntimeWarning)
    except Exception:
        pass


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, predicate=_is_parameter,
                     filename=filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, predicate=_is_parameter,
                     filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, predicate=_is_persistable,
                     filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, predicate=_is_persistable,
                     filename=filename)


# -- unified fluid.save / fluid.load (reference: io.py:1598/:1662) ---------
def save(program: Program, model_path: str):
    params = {v.name: as_numpy(global_scope().get(v.name))
              for v in program.list_vars()
              if _is_parameter(v) and global_scope().has(v.name)}
    others = {v.name: as_numpy(global_scope().get(v.name))
              for v in program.list_vars()
              if _is_persistable(v) and not _is_parameter(v)
              and global_scope().has(v.name)}
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    atomic_savez(model_path + ".pdparams.npz", **params)
    atomic_savez(model_path + ".pdopt.npz", **others)
    atomic_write_bytes(model_path + ".pdmodel",
                       program.serialize_to_string())


def load(program: Program, model_path: str, executor=None, var_list=None):
    scope = global_scope()
    for suffix in (".pdparams.npz", ".pdopt.npz"):
        path = model_path + suffix
        if os.path.exists(path):
            with np.load(path, allow_pickle=False) as z:
                for name in z.files:
                    scope.set(name, np.asarray(z[name]))


# -- inference model export (reference: io.py:1093/:1303) ------------------
def _prune_for_inference(program: Program, feed_names, fetch_names) -> Program:
    """Backward DCE from fetches via the shared pass infra
    (framework/ir.py: remove_training_ops_pass + strict DCE)."""
    from .framework.ir import PassManager, get_pass

    pruned = program.clone(for_test=True)
    PassManager([
        "remove_training_ops_pass",
        get_pass("dead_code_elimination_pass", targets=list(fetch_names),
                 strict=True),
    ]).apply(pruned)
    block = pruned.global_block()
    # drop vars no longer referenced (keeps the exported desc minimal and
    # makes load_inference_model's persistable scan exact)
    referenced = set(feed_names) | set(fetch_names)
    for op_ in block.ops:
        referenced.update(op_.input_arg_names)
        referenced.update(op_.output_arg_names)
    for name in list(block.vars):
        if name not in referenced:
            del block.vars[name]
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
    export_for_deployment=True,
    program_only=False,
):
    """reference: io.py:1093."""
    main_program = main_program or default_main_program()
    fetch_names = [v.name if isinstance(v, Variable) else v for v in target_vars]
    pruned = _prune_for_inference(main_program, feeded_var_names, fetch_names)
    os.makedirs(dirname, exist_ok=True)
    model_filename = model_filename or "__model__"
    meta = {
        "program": json.loads(pruned.serialize_to_string().decode("utf-8")),
        "feed_names": list(feeded_var_names),
        "fetch_names": fetch_names,
    }
    atomic_write_bytes(os.path.join(dirname, model_filename),
                       json.dumps(meta).encode())
    if not program_only:
        # persistables referenced by the pruned program (reference saves
        # persistables, not only Parameter instances — io.py:1093)
        needed = {n for op_ in pruned.global_block().ops
                  for n in op_.input_arg_names}
        vars_ = [v for v in main_program.list_vars()
                 if _is_persistable(v) and v.name in needed]
        save_vars(executor, dirname, main_program, vars=vars_,
                  filename=params_filename)
    return fetch_names


def load_inference_model(
    dirname,
    executor,
    model_filename=None,
    params_filename=None,
):
    """reference: io.py:1303 — returns (program, feed_names, fetch_vars)."""
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename)) as f:
        meta = json.load(f)
    program = Program.from_desc_dict(meta["program"])
    load_vars(executor, dirname, program, predicate=_is_persistable,
              filename=params_filename)
    block = program.global_block()
    fetch_vars = [block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


# reference: fluid/io.py re-exports the data-loading surface
from .reader import DataLoader, PyReader, DataFeeder  # noqa: E402
