from . import fleet
from . import complex  # noqa: A004
