from . import fleet
