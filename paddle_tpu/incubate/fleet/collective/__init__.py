"""Fleet collective mode: SPMD data-parallel training over the mesh.

Reference: python/paddle/fluid/incubate/fleet/collective/__init__.py
(Collective fleet:64, CollectiveOptimizer:384, DistributedStrategy:334,
_try_to_compile:516-540 with hierarchical-allreduce setup).  TPU-native:
minimize() runs the user optimizer then applies the GradAllReduce
transpile; the rewritten program executes as one SPMD program under
shard_map (the c_allreduce_sum ops lower to psum on ICI), so
hierarchical allreduce / multi-ring / nccl_comm_num knobs become mesh
shape choices (ICI×DCN axes) rather than comm objects.
"""
from __future__ import annotations

import os

from ....framework.core import default_main_program, default_startup_program
from ....parallel.compiled_program import BuildStrategy, ExecutionStrategy
from ....parallel import mesh as mesh_mod
from ....transpiler.collective import GradAllReduce, LocalSGD
from ..base.fleet_base import DistributedOptimizer, Fleet, Mode


class DistributedStrategy:
    """reference: fleet/collective/__init__.py:334."""

    def __init__(self):
        self.use_local_sgd = False
        self.local_sgd_k_steps = 1
        self.use_dgc = False
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 8
        self.fuse_all_reduce_ops = True
        # ZeRO-1 optimizer-state sharding (reference: Fleet `sharding`
        # strategy) — maps onto FLAGS_dp_sharding; None keeps the
        # process-start flag value.  Truthy == stage 1.
        self.sharding = None
        # Fluid sharding_stage analog (reference: fleet sharding
        # strategy's stage knob / DygraphShardingOptimizer stages):
        # 1 = optimizer state, 2 = + gradients (reduce-scatter into the
        # shard update), 3 = + parameters (just-in-time all-gather).
        # Overrides `sharding` when set; None defers to it.
        self.sharding_stage = None
        # backward-overlap scheduling of fused grad buckets (reference:
        # multi_devices_graph_pass allreduce ordering) — None keeps the
        # FLAGS_dp_comm_overlap default
        self.comm_overlap = None
        # bucket size for the coalesced grad collective (reference:
        # fuse_grad_size_in_MB build-strategy knob) — None keeps the
        # FLAGS_fuse_grad_size_in_MB default; "auto" (r9) derives
        # variable bucket boundaries from the modeled backward timeline
        # (utils/cost_model.py) instead of a fixed threshold
        self.fuse_grad_size_in_MB = None
        # EQuARX-style wire compression for fused buckets: "none"|"bf16"
        self.grad_compress = None
        # ZeRO-3 parameter-prefetch window (r9): hoist each sharded
        # param's all-gather this many ops ahead of its first consumer
        # per direction — None keeps the FLAGS_dp_prefetch_depth
        # default, 0 restores the just-in-time per-consumer gather
        self.prefetch_depth = None
        # cost-model-driven auto-parallel plan search (r16,
        # parallel/plan_search.py): "auto" searches ZeRO stage x bucket
        # threshold x prefetch depth x overlap per (program, mesh) and
        # applies the modeled-time argmin that fits
        # FLAGS_hbm_budget_mb; it overrides the four knobs above.
        # None keeps the FLAGS_dp_plan default ("" = flag-driven).
        self.dp_plan = None
        self.exec_strategy = ExecutionStrategy()
        self.build_strategy = BuildStrategy()
        self.forward_recompute = False
        self.recompute_checkpoints = []


class Collective(Fleet):
    """reference: fleet/collective/__init__.py:64."""

    def __init__(self):
        super().__init__(Mode.COLLECTIVE)
        self._local_ip = 0
        self.startup_program = None
        self.main_program = None
        # checkpoint-number allocator (per root path): numbers must be
        # monotonic over IN-FLIGHT async saves too — a directory whose
        # manifest has not landed yet is invisible to the newest-valid
        # election but its number is still taken
        self._alloc_nos = {}

    def init_worker(self):
        pass

    def init_server(self, model_dir=None):
        raise NotImplementedError("Collective mode has no servers")

    def run_server(self):
        raise NotImplementedError("Collective mode has no servers")

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy, fleet=self)
        return self._optimizer

    def compiled_program(self, loss_name=None):
        """The ParallelExecutor-compat execution handle for the transpiled
        program (reference runs fleet.main_program in N processes; here one
        SPMD program over the mesh)."""
        from ....parallel.compiled_program import CompiledProgram

        return CompiledProgram(self.main_program).with_data_parallel(
            loss_name=loss_name
        )

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io

        return io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program or self.main_program,
        )

    def save_persistables(self, executor, dirname, main_program=None,
                          filename=None):
        from .... import io

        return io.save_persistables(executor, dirname,
                                    main_program or self.main_program, filename)

    # -- epoch checkpoints (reference: fleet/collective/__init__.py:206-287)
    _checkpoint_prefix = "__paddle_fleet_checkpoint__"
    _param_file_name = "_paddle_fleet_param__"

    def _save_train_status(self, path, train_status):
        import json
        import os

        with open(os.path.join(path, "fleet_train_status"), "w") as f:
            json.dump(train_status.to_dict(), f)

    def _load_train_status(self, path):
        import json
        import os

        fname = os.path.join(path, "fleet_train_status")
        if not os.path.isfile(fname):
            return TrainStatus()
        with open(fname) as f:
            d = json.load(f)
        assert "epoch_no" in d and d["epoch_no"] >= 0, \
            f"invalid train_status file: {d}"
        return TrainStatus.from_dict(d)

    def _checkpoint_numbers(self, root_path, fs, valid_only=True):
        """Sorted checkpoint numbers under root.  ``valid_only`` skips
        stray suffixes and any dir without a commit record (a
        ``manifest.json`` from the sharded format, or the legacy
        ``fleet_train_status`` marker) — a save that crashed before its
        manifest landed can never be selected as "newest"."""
        from ....checkpoint import MANIFEST

        nos = []
        for d in fs.list_dirs(root_path):
            g = d.split(".")
            if len(g) != 2 or g[0] != self._checkpoint_prefix:
                continue
            try:
                n = int(g[1])
            except ValueError:
                continue  # stray suffix (".tmp", ".abc", ...)
            if valid_only:
                p = f"{root_path}/{d}"
                if not (fs.stat(f"{p}/{MANIFEST}")
                        or fs.stat(f"{p}/fleet_train_status")):
                    continue  # crashed/in-progress save: no commit record
            nos.append(n)
        return sorted(nos)

    def _get_last_checkpoint_no(self, root_path, fs):
        nos = self._checkpoint_numbers(root_path, fs)
        return nos[-1] if nos else -1

    def clean_redundant_check_points(self, root_path, fs=None,
                                     checkpoint_num=1):
        from ..utils.fs import LocalFS

        fs = fs or LocalFS()
        max_no = self._get_last_checkpoint_no(root_path, fs)
        if max_no < 0:
            return
        checkpoint_num = max(checkpoint_num, 1)
        # rotation sweeps INVALID numbered dirs too (valid_only=False):
        # a crashed save's debris must not accumulate, it just must
        # never win the newest-checkpoint election above
        for n in self._checkpoint_numbers(root_path, fs, valid_only=False):
            if n <= max_no - checkpoint_num:
                fs.rmr(f"{root_path}/{self._checkpoint_prefix}.{n}")

    def _checkpoint_state(self, main_program, include_rng=True):
        """Persistable scope state for a checkpoint, captured with the
        non-blocking executor snapshot (D2H copies start immediately;
        sharded jax values stay sharded — checkpoint.py writes each
        rank's resident rows only)."""
        from ....executor import snapshot_scope_state
        from ....framework.scope import global_scope
        from ....io import get_program_persistable_vars

        scope = global_scope()
        names = [v.name for v in get_program_persistable_vars(main_program)]
        if include_rng:
            from ....ops import registry

            rng = registry.LowerCtx.RNG_VAR
            if scope.has(rng):
                names.append(rng)
        return snapshot_scope_state(scope, names)

    def save_check_point(self, executor, path, train_status,
                         main_program=None, fs=None,
                         local_cache_path=".cache",
                         remain_all_checkpoint=True, writer=None):
        """Save scope persistables + train status into
        path/<prefix>.<n> as a sharded atomic checkpoint
        (paddle_tpu/checkpoint.py): per-rank shard files for ZeRO-
        sharded state, per-file checksums, manifest committed last.
        ``writer`` (an AsyncCheckpointWriter) makes the save
        non-blocking on a local FS; remote FSes stay synchronous (the
        upload needs the files on disk)."""
        from ....checkpoint import save_sharded
        from ....utils.flags import flag
        from ..utils.fs import LocalFS

        fs = fs or LocalFS()
        main_program = main_program or self.main_program
        if not fs.stat(path):
            fs.mkdir(path)
        all_nos = self._checkpoint_numbers(path, fs, valid_only=False)
        next_no = max(all_nos[-1] if all_nos else -1,
                      self._alloc_nos.get(path, -1)) + 1
        self._alloc_nos[path] = next_no
        real_path = f"{path}/{self._checkpoint_prefix}.{next_no}"
        state = self._checkpoint_state(main_program)
        train = train_status.to_dict()
        try:
            from ....parallel.mesh import default_dp_mesh

            mesh = default_dp_mesh()
            mesh_info = {"axes": list(mesh.axis_names),
                         "shape": [int(s) for s in mesh.devices.shape]}
        except Exception:
            mesh_info = None
        extra = {"stage": int(flag("dp_sharding") or 0), "mesh": mesh_info}

        if fs.need_upload_download():
            local_fs = LocalFS()
            tmp_path = f"{real_path}.tmp"
            saved_path = (f"{local_cache_path}/{self._checkpoint_prefix}"
                          f".{next_no}.saved_cache")
            local_fs.delete(saved_path)
            local_fs.mkdir(saved_path)
            save_sharded(saved_path, state, train=train, extra=extra)
            fs.delete(tmp_path)
            fs.upload(saved_path, tmp_path)
            fs.mv(tmp_path, real_path)
        else:
            # manifest-last IS the commit: write in place.  The number
            # is freshly allocated (never reused in-process, in-flight
            # async dirs counted), so nothing can be squatting on it
            # except a dead EARLIER process's debris — rotation sweeps
            # that; the newest-valid election already ignores it.
            if writer is not None:
                writer.save(real_path, state, train=train, extra=extra)
            else:
                save_sharded(real_path, state, train=train, extra=extra)
        if not remain_all_checkpoint:
            self.clean_redundant_check_points(path, fs=fs)
        return real_path

    def _load_one_checkpoint(self, executor, load_path, main_program):
        """Load a single checkpoint dir (sharded-manifest or legacy
        format) into the global scope; returns its TrainStatus.  Raises
        CheckpointError on integrity failure."""
        import os

        from ....checkpoint import MANIFEST, load_sharded
        from ....framework.scope import global_scope

        if os.path.isfile(os.path.join(load_path, MANIFEST)):
            state, manifest = load_sharded(load_path)
            scope = global_scope()
            for name, val in state.items():
                scope.set(name, val)
            return TrainStatus.from_dict(manifest.get("train", {}))
        from .... import io

        io.load_persistables(executor=executor, dirname=load_path,
                             main_program=main_program,
                             filename=self._param_file_name)
        return self._load_train_status(load_path)

    def load_check_point(self, executor, path, trainer_id=0,
                         main_program=None, fs=None,
                         local_cache_path=".cache", ignore_empty=True):
        """Load the newest VALID checkpoint; returns its TrainStatus
        (or None when the directory has no checkpoints and
        ignore_empty).  A checkpoint that fails integrity validation
        (truncated/corrupt data file, torn manifest) is rejected and
        the previous one is tried instead — newest-first until one
        loads."""
        import warnings

        from ....checkpoint import CheckpointError
        from ..utils.fs import LocalFS

        fs = fs or LocalFS()
        main_program = main_program or self.main_program
        nos = self._checkpoint_numbers(path, fs)
        if not ignore_empty:
            assert nos, "Can't find checkpoint"
        last_err = None
        for no in reversed(nos):
            real_path = f"{path}/{self._checkpoint_prefix}.{no}"
            load_path = real_path
            if fs.need_upload_download():
                local_fs = LocalFS()
                cache = (f"{local_cache_path}/{self._checkpoint_prefix}"
                         f".{no}.load_cache.{trainer_id}")
                local_fs.delete(cache)
                fs.download(real_path, cache)
                load_path = cache
            try:
                return self._load_one_checkpoint(executor, load_path,
                                                 main_program)
            except CheckpointError as e:
                last_err = e
                warnings.warn(
                    f"checkpoint {real_path} rejected ({e}); falling "
                    f"back to the previous one", RuntimeWarning)
        if last_err is not None and not ignore_empty:
            raise last_err
        return None


class TrainStatus:
    """reference: fleet/collective/__init__.py TrainStatus — the epoch
    counter persisted next to each checkpoint, grown (r11) into the
    full exact-resume record: global step, reader position (batches
    consumed, so a resumed run feeds the SAME next batch), an optional
    serialized host-side RNG state, and the lr-scheduler counters that
    live outside the scope (scope-resident counters like the Adam beta
    pows checkpoint with the state itself)."""

    def __init__(self, epoch_no=-1, step_no=-1, reader_offset=0,
                 rng_state=None, lr_counters=None):
        self._epoch_no = epoch_no
        self.step_no = step_no
        self.reader_offset = reader_offset
        self.rng_state = rng_state          # JSON-able, e.g. key_data list
        self.lr_counters = dict(lr_counters or {})

    def next(self):
        return self._epoch_no + 1

    def to_dict(self):
        return {"epoch_no": self._epoch_no, "step_no": self.step_no,
                "reader_offset": self.reader_offset,
                "rng_state": self.rng_state,
                "lr_counters": dict(self.lr_counters)}

    @classmethod
    def from_dict(cls, d):
        """Back-compat: legacy records carry only epoch_no."""
        return cls(epoch_no=int(d.get("epoch_no", -1)),
                   step_no=int(d.get("step_no", -1)),
                   reader_offset=int(d.get("reader_offset", 0)),
                   rng_state=d.get("rng_state"),
                   lr_counters=d.get("lr_counters") or {})

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and \
            self.to_dict() == other.to_dict()

    def __ne__(self, other):
        return not self == other


fleet = Collective()


class CollectiveOptimizer(DistributedOptimizer):
    """reference: fleet/collective/__init__.py:384."""

    def __init__(self, optimizer, strategy=None, fleet=None):
        super().__init__(optimizer, strategy or DistributedStrategy())
        self._fleet = fleet

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        f = self._fleet
        main_program = loss.block.program
        startup_program = startup_program or default_startup_program()

        strategy = self._strategy
        # strategy knobs -> framework flags (the executor's IR pipeline
        # and the DP runner read flags, like the reference's
        # build_strategy -> pass-attr plumbing)
        from ....utils import flags as _flags

        # the strategy is the config of record: EVERY knob is set both
        # ways (flags are process-global — a later optimizer with
        # default settings must really clear what a previous one set,
        # or job B silently trains with job A's sharding/compression)
        # knobs left unconfigured (None) restore the PROCESS-START value
        # (defaults + FLAGS_* env), not the hard-coded default — an
        # operator's FLAGS_dp_grad_compress=bf16 env setting survives a
        # default strategy
        if not getattr(strategy, "fuse_all_reduce_ops", True):
            fuse_mb = 0.0
        elif getattr(strategy, "fuse_grad_size_in_MB", None) is not None:
            fuse_mb = strategy.fuse_grad_size_in_MB
            if not (isinstance(fuse_mb, str)
                    and fuse_mb.strip().lower() == "auto"):
                fuse_mb = float(fuse_mb)
        else:
            fuse_mb = _flags._INITIAL["FLAGS_fuse_grad_size_in_MB"]
        compress = getattr(strategy, "grad_compress", None)
        sharding = getattr(strategy, "sharding", None)
        stage = getattr(strategy, "sharding_stage", None)
        if stage is not None:
            dp_sharding = int(stage)
        elif sharding is not None:
            dp_sharding = int(bool(sharding))
        else:
            dp_sharding = _flags._INITIAL["FLAGS_dp_sharding"]
        overlap = getattr(strategy, "comm_overlap", None)
        prefetch = getattr(strategy, "prefetch_depth", None)
        dp_plan = getattr(strategy, "dp_plan", None)
        _flags.set_flags({
            "dp_sharding": dp_sharding,
            "fuse_grad_size_in_MB": fuse_mb,
            "dp_grad_compress": str(compress) if compress is not None
            else _flags._INITIAL["FLAGS_dp_grad_compress"],
            "dp_comm_overlap": bool(overlap) if overlap is not None
            else _flags._INITIAL["FLAGS_dp_comm_overlap"],
            "dp_prefetch_depth": int(prefetch) if prefetch is not None
            else _flags._INITIAL["FLAGS_dp_prefetch_depth"],
            "dp_plan": str(dp_plan) if dp_plan is not None
            else _flags._INITIAL["FLAGS_dp_plan"],
        })
        if getattr(strategy, "use_dgc", False):
            # reference: fleet swaps Momentum for DGCMomentum when
            # use_dgc is set; DGC inserts its own (sparse) exchange, so
            # no GradAllReduce transpile on top
            from ....optimizer import DGCMomentumOptimizer, MomentumOptimizer

            opt = self._optimizer
            if not isinstance(opt, MomentumOptimizer):
                raise ValueError(
                    "use_dgc requires a Momentum optimizer (reference "
                    "fleet asserts the same); got "
                    f"{type(opt).__name__}")
            if not isinstance(opt, DGCMomentumOptimizer):
                self._optimizer = DGCMomentumOptimizer(
                    opt._learning_rate, opt._momentum,
                    use_nesterov=opt._use_nesterov,
                    rampup_begin_step=getattr(
                        strategy, "dgc_rampup_begin_step", 0),
                    sparsity=getattr(strategy, "dgc_sparsity", (0.999,)),
                    regularization=opt.regularization,
                    grad_clip=getattr(opt, "_grad_clip", None))

        optimize_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )

        nranks = f.worker_num() if f is not None and f._is_initialized else 1
        rank = f.worker_index() if f is not None and f._is_initialized else 0
        # SPMD: ranks in one process == devices on the mesh
        mesh = mesh_mod.default_dp_mesh()
        nranks = max(nranks, mesh.size)

        if getattr(strategy, "use_dgc", False):
            if f is not None:
                f.main_program = main_program
                f.startup_program = startup_program
            return optimize_ops, params_grads
        if strategy.use_local_sgd:
            t = LocalSGD(nrings=strategy.nccl_comm_num,
                         k_steps=strategy.local_sgd_k_steps)
        elif strategy.use_hierarchical_allreduce:
            # hybrid ICI x DCN mesh: (inter, intra) axes; the intra axis
            # is the fast in-node/ICI ring of inter_nranks devices
            intra = strategy.hierarchical_allreduce_inter_nranks
            assert nranks % intra == 0, (
                f"hierarchical allreduce: nranks {nranks} not divisible "
                f"by inter_nranks {intra}")
            mesh_mod.registry().create_mesh(
                (nranks // intra, intra), ("inter", "intra"),
                name="hierarchical")
            t = GradAllReduce(nrings=strategy.nccl_comm_num,
                              hierarchical=True, intra_nranks=intra)
        else:
            t = GradAllReduce(nrings=strategy.nccl_comm_num)
        t.transpile(
            startup_program=startup_program,
            main_program=main_program,
            rank=rank,
            endpoints=f.worker_endpoints() if f and f._is_initialized else None,
            nranks=nranks,
        )
        if f is not None:
            f.main_program = main_program
            f.startup_program = startup_program
        return optimize_ops, params_grads
