"""Fleet collective mode: SPMD data-parallel training over the mesh.

Reference: python/paddle/fluid/incubate/fleet/collective/__init__.py
(Collective fleet:64, CollectiveOptimizer:384, DistributedStrategy:334,
_try_to_compile:516-540 with hierarchical-allreduce setup).  TPU-native:
minimize() runs the user optimizer then applies the GradAllReduce
transpile; the rewritten program executes as one SPMD program under
shard_map (the c_allreduce_sum ops lower to psum on ICI), so
hierarchical allreduce / multi-ring / nccl_comm_num knobs become mesh
shape choices (ICI×DCN axes) rather than comm objects.
"""
from __future__ import annotations

import os

from ....framework.core import default_main_program, default_startup_program
from ....parallel.compiled_program import BuildStrategy, ExecutionStrategy
from ....parallel import mesh as mesh_mod
from ....transpiler.collective import GradAllReduce, LocalSGD
from ..base.fleet_base import DistributedOptimizer, Fleet, Mode


class DistributedStrategy:
    """reference: fleet/collective/__init__.py:334."""

    def __init__(self):
        self.use_local_sgd = False
        self.local_sgd_k_steps = 1
        self.use_dgc = False
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 8
        self.fuse_all_reduce_ops = True
        # ZeRO-1 optimizer-state sharding (reference: Fleet `sharding`
        # strategy) — maps onto FLAGS_dp_sharding; None keeps the
        # process-start flag value.  Truthy == stage 1.
        self.sharding = None
        # Fluid sharding_stage analog (reference: fleet sharding
        # strategy's stage knob / DygraphShardingOptimizer stages):
        # 1 = optimizer state, 2 = + gradients (reduce-scatter into the
        # shard update), 3 = + parameters (just-in-time all-gather).
        # Overrides `sharding` when set; None defers to it.
        self.sharding_stage = None
        # backward-overlap scheduling of fused grad buckets (reference:
        # multi_devices_graph_pass allreduce ordering) — None keeps the
        # FLAGS_dp_comm_overlap default
        self.comm_overlap = None
        # bucket size for the coalesced grad collective (reference:
        # fuse_grad_size_in_MB build-strategy knob) — None keeps the
        # FLAGS_fuse_grad_size_in_MB default; "auto" (r9) derives
        # variable bucket boundaries from the modeled backward timeline
        # (utils/cost_model.py) instead of a fixed threshold
        self.fuse_grad_size_in_MB = None
        # EQuARX-style wire compression for fused buckets: "none"|"bf16"
        self.grad_compress = None
        # ZeRO-3 parameter-prefetch window (r9): hoist each sharded
        # param's all-gather this many ops ahead of its first consumer
        # per direction — None keeps the FLAGS_dp_prefetch_depth
        # default, 0 restores the just-in-time per-consumer gather
        self.prefetch_depth = None
        self.exec_strategy = ExecutionStrategy()
        self.build_strategy = BuildStrategy()
        self.forward_recompute = False
        self.recompute_checkpoints = []


class Collective(Fleet):
    """reference: fleet/collective/__init__.py:64."""

    def __init__(self):
        super().__init__(Mode.COLLECTIVE)
        self._local_ip = 0
        self.startup_program = None
        self.main_program = None

    def init_worker(self):
        pass

    def init_server(self, model_dir=None):
        raise NotImplementedError("Collective mode has no servers")

    def run_server(self):
        raise NotImplementedError("Collective mode has no servers")

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy, fleet=self)
        return self._optimizer

    def compiled_program(self, loss_name=None):
        """The ParallelExecutor-compat execution handle for the transpiled
        program (reference runs fleet.main_program in N processes; here one
        SPMD program over the mesh)."""
        from ....parallel.compiled_program import CompiledProgram

        return CompiledProgram(self.main_program).with_data_parallel(
            loss_name=loss_name
        )

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io

        return io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program or self.main_program,
        )

    def save_persistables(self, executor, dirname, main_program=None,
                          filename=None):
        from .... import io

        return io.save_persistables(executor, dirname,
                                    main_program or self.main_program, filename)

    # -- epoch checkpoints (reference: fleet/collective/__init__.py:206-287)
    _checkpoint_prefix = "__paddle_fleet_checkpoint__"
    _param_file_name = "_paddle_fleet_param__"

    def _save_train_status(self, path, train_status):
        import json
        import os

        with open(os.path.join(path, "fleet_train_status"), "w") as f:
            json.dump({"epoch_no": train_status._epoch_no}, f)

    def _load_train_status(self, path):
        import json
        import os

        r = TrainStatus()
        fname = os.path.join(path, "fleet_train_status")
        if not os.path.isfile(fname):
            return r
        with open(fname) as f:
            d = json.load(f)
        assert "epoch_no" in d and d["epoch_no"] >= 0, \
            f"invalid train_status file: {d}"
        r._epoch_no = d["epoch_no"]
        return r

    def _get_last_checkpoint_no(self, root_path, fs):
        max_no = -1
        for d in fs.list_dirs(root_path):
            g = d.split(".")
            if len(g) != 2 or g[0] != self._checkpoint_prefix:
                continue
            try:
                max_no = max(max_no, int(g[1]))
            except ValueError:
                continue
        return max_no

    def clean_redundant_check_points(self, root_path, fs=None,
                                     checkpoint_num=1):
        from ..utils.fs import LocalFS

        fs = fs or LocalFS()
        max_no = self._get_last_checkpoint_no(root_path, fs)
        if max_no < 0:
            return
        checkpoint_num = max(checkpoint_num, 1)
        for d in fs.list_dirs(root_path):
            g = d.split(".")
            if len(g) != 2 or g[0] != self._checkpoint_prefix:
                continue
            try:
                n = int(g[1])
            except ValueError:
                continue
            if n <= max_no - checkpoint_num:
                fs.rmr(f"{root_path}/{self._checkpoint_prefix}.{n}")

    def save_check_point(self, executor, path, train_status,
                         main_program=None, fs=None,
                         local_cache_path=".cache",
                         remain_all_checkpoint=True):
        """Save persistables + epoch number into path/<prefix>.<n>
        atomically (tmp dir then mv), optionally rotating old epochs."""
        from ..utils.fs import LocalFS

        fs = fs or LocalFS()
        main_program = main_program or self.main_program
        if not fs.stat(path):
            fs.mkdir(path)
        max_no = self._get_last_checkpoint_no(path, fs=fs)
        real_path = f"{path}/{self._checkpoint_prefix}.{max_no + 1}"
        tmp_path = f"{real_path}.tmp"
        local_fs = LocalFS()

        saved_path = tmp_path
        if fs.need_upload_download():
            saved_path = (f"{local_cache_path}/{self._checkpoint_prefix}"
                          f".{max_no + 1}.saved_cache")
            local_fs.mkdir(saved_path)
        else:
            local_fs.mkdir(saved_path)

        self.save_persistables(executor=executor, dirname=saved_path,
                               main_program=main_program,
                               filename=self._param_file_name)
        self._save_train_status(path=saved_path, train_status=train_status)

        if fs.need_upload_download():
            fs.delete(tmp_path)
            fs.upload(saved_path, tmp_path)
        fs.mv(tmp_path, real_path)
        if not remain_all_checkpoint:
            self.clean_redundant_check_points(path, fs=fs)
        return real_path

    def load_check_point(self, executor, path, trainer_id=0,
                         main_program=None, fs=None,
                         local_cache_path=".cache", ignore_empty=True):
        """Load the newest checkpoint; returns its TrainStatus (or None
        when the directory has no checkpoints and ignore_empty)."""
        from .... import io
        from ..utils.fs import LocalFS

        fs = fs or LocalFS()
        max_no = self._get_last_checkpoint_no(path, fs)
        if not ignore_empty:
            assert max_no >= 0, "Can't find checkpoint"
        if max_no < 0:
            return None
        real_path = f"{path}/{self._checkpoint_prefix}.{max_no}"
        load_path = real_path
        if fs.need_upload_download():
            local_fs = LocalFS()
            cache = (f"{local_cache_path}/{self._checkpoint_prefix}"
                     f".{max_no}.load_cache.{trainer_id}")
            local_fs.delete(cache)
            fs.download(real_path, cache)
            load_path = cache
        io.load_persistables(executor=executor, dirname=load_path,
                             main_program=main_program or self.main_program,
                             filename=self._param_file_name)
        return self._load_train_status(load_path)


class TrainStatus:
    """reference: fleet/collective/__init__.py TrainStatus — the epoch
    counter persisted next to each checkpoint."""

    def __init__(self, epoch_no=-1):
        self._epoch_no = epoch_no

    def next(self):
        return self._epoch_no + 1

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and \
            self._epoch_no == other._epoch_no

    def __ne__(self, other):
        return not self == other


fleet = Collective()


class CollectiveOptimizer(DistributedOptimizer):
    """reference: fleet/collective/__init__.py:384."""

    def __init__(self, optimizer, strategy=None, fleet=None):
        super().__init__(optimizer, strategy or DistributedStrategy())
        self._fleet = fleet

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        f = self._fleet
        main_program = loss.block.program
        startup_program = startup_program or default_startup_program()

        strategy = self._strategy
        # strategy knobs -> framework flags (the executor's IR pipeline
        # and the DP runner read flags, like the reference's
        # build_strategy -> pass-attr plumbing)
        from ....utils import flags as _flags

        # the strategy is the config of record: EVERY knob is set both
        # ways (flags are process-global — a later optimizer with
        # default settings must really clear what a previous one set,
        # or job B silently trains with job A's sharding/compression)
        # knobs left unconfigured (None) restore the PROCESS-START value
        # (defaults + FLAGS_* env), not the hard-coded default — an
        # operator's FLAGS_dp_grad_compress=bf16 env setting survives a
        # default strategy
        if not getattr(strategy, "fuse_all_reduce_ops", True):
            fuse_mb = 0.0
        elif getattr(strategy, "fuse_grad_size_in_MB", None) is not None:
            fuse_mb = strategy.fuse_grad_size_in_MB
            if not (isinstance(fuse_mb, str)
                    and fuse_mb.strip().lower() == "auto"):
                fuse_mb = float(fuse_mb)
        else:
            fuse_mb = _flags._INITIAL["FLAGS_fuse_grad_size_in_MB"]
        compress = getattr(strategy, "grad_compress", None)
        sharding = getattr(strategy, "sharding", None)
        stage = getattr(strategy, "sharding_stage", None)
        if stage is not None:
            dp_sharding = int(stage)
        elif sharding is not None:
            dp_sharding = int(bool(sharding))
        else:
            dp_sharding = _flags._INITIAL["FLAGS_dp_sharding"]
        overlap = getattr(strategy, "comm_overlap", None)
        prefetch = getattr(strategy, "prefetch_depth", None)
        _flags.set_flags({
            "dp_sharding": dp_sharding,
            "fuse_grad_size_in_MB": fuse_mb,
            "dp_grad_compress": str(compress) if compress is not None
            else _flags._INITIAL["FLAGS_dp_grad_compress"],
            "dp_comm_overlap": bool(overlap) if overlap is not None
            else _flags._INITIAL["FLAGS_dp_comm_overlap"],
            "dp_prefetch_depth": int(prefetch) if prefetch is not None
            else _flags._INITIAL["FLAGS_dp_prefetch_depth"],
        })
        if getattr(strategy, "use_dgc", False):
            # reference: fleet swaps Momentum for DGCMomentum when
            # use_dgc is set; DGC inserts its own (sparse) exchange, so
            # no GradAllReduce transpile on top
            from ....optimizer import DGCMomentumOptimizer, MomentumOptimizer

            opt = self._optimizer
            if not isinstance(opt, MomentumOptimizer):
                raise ValueError(
                    "use_dgc requires a Momentum optimizer (reference "
                    "fleet asserts the same); got "
                    f"{type(opt).__name__}")
            if not isinstance(opt, DGCMomentumOptimizer):
                self._optimizer = DGCMomentumOptimizer(
                    opt._learning_rate, opt._momentum,
                    use_nesterov=opt._use_nesterov,
                    rampup_begin_step=getattr(
                        strategy, "dgc_rampup_begin_step", 0),
                    sparsity=getattr(strategy, "dgc_sparsity", (0.999,)),
                    regularization=opt.regularization,
                    grad_clip=getattr(opt, "_grad_clip", None))

        optimize_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )

        nranks = f.worker_num() if f is not None and f._is_initialized else 1
        rank = f.worker_index() if f is not None and f._is_initialized else 0
        # SPMD: ranks in one process == devices on the mesh
        mesh = mesh_mod.default_dp_mesh()
        nranks = max(nranks, mesh.size)

        if getattr(strategy, "use_dgc", False):
            if f is not None:
                f.main_program = main_program
                f.startup_program = startup_program
            return optimize_ops, params_grads
        if strategy.use_local_sgd:
            t = LocalSGD(nrings=strategy.nccl_comm_num,
                         k_steps=strategy.local_sgd_k_steps)
        elif strategy.use_hierarchical_allreduce:
            # hybrid ICI x DCN mesh: (inter, intra) axes; the intra axis
            # is the fast in-node/ICI ring of inter_nranks devices
            intra = strategy.hierarchical_allreduce_inter_nranks
            assert nranks % intra == 0, (
                f"hierarchical allreduce: nranks {nranks} not divisible "
                f"by inter_nranks {intra}")
            mesh_mod.registry().create_mesh(
                (nranks // intra, intra), ("inter", "intra"),
                name="hierarchical")
            t = GradAllReduce(nrings=strategy.nccl_comm_num,
                              hierarchical=True, intra_nranks=intra)
        else:
            t = GradAllReduce(nrings=strategy.nccl_comm_num)
        t.transpile(
            startup_program=startup_program,
            main_program=main_program,
            rank=rank,
            endpoints=f.worker_endpoints() if f and f._is_initialized else None,
            nranks=nranks,
        )
        if f is not None:
            f.main_program = main_program
            f.startup_program = startup_program
        return optimize_ops, params_grads
