from . import base
from . import collective
from . import parameter_server
