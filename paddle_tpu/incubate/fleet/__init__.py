from . import base
from . import collective
