"""Fleet base: the unified distributed-training façade.

Reference: python/paddle/fluid/incubate/fleet/base/fleet_base.py — Fleet
abstract base (init/init_worker/init_server/distributed_optimizer/
minimize/save_*) + DistributedOptimizer base.
"""
from __future__ import annotations

import abc
from typing import Optional

from .role_maker import RoleMakerBase


class Mode:
    TRANSPILER = 1
    PSLIB = 2
    COLLECTIVE = 3


class Fleet(abc.ABC):
    def __init__(self, mode):
        self._is_initialized = False
        self._mode = mode
        self._optimizer = None
        self._role_maker: Optional[RoleMakerBase] = None

    # -- role facts ------------------------------------------------------
    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return self._role_maker.is_server()

    # -- lifecycle -------------------------------------------------------
    def init(self, role_maker: Optional[RoleMakerBase] = None):
        from .role_maker import PaddleCloudRoleMaker

        if role_maker is None:
            role_maker = PaddleCloudRoleMaker(
                is_collective=(self._mode == Mode.COLLECTIVE)
            )
        self._role_maker = role_maker
        role_maker.generate_role()
        self._is_initialized = True

    @abc.abstractmethod
    def init_worker(self):
        ...

    @abc.abstractmethod
    def init_server(self, model_dir=None):
        ...

    @abc.abstractmethod
    def run_server(self):
        ...

    @abc.abstractmethod
    def stop_worker(self):
        ...

    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        ...

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._optimizer.minimize(loss, startup_program, parameter_list,
                                        no_grad_set)


class DistributedOptimizer(abc.ABC):
    """reference: fleet_base.py DistributedOptimizer."""

    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program, parameter_list,
                                        no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    @abc.abstractmethod
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ...
