"""Role makers: cluster topology discovery.

Capability parity with reference: python/paddle/fluid/incubate/fleet/base/
role_maker.py (RoleMakerBase:68, MPIRoleMaker:186, PaddleCloudRoleMaker
:477, UserDefinedRoleMaker:988, UserDefinedCollectiveRoleMaker:1064,
GeneralRoleMaker:578 with Gloo/HTTP rendezvous).  TPU-native: the
rendezvous mechanism is the JAX coordination service
(jax.distributed.initialize) instead of MPI/Gloo/HTTP; env-variable role
discovery (PADDLE_TRAINER_ID & co) is kept verbatim so PaddleCloud-style
launchers keep working.
"""
from __future__ import annotations

import os
from enum import IntEnum
from typing import List, Optional


class Role(IntEnum):
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints: List[str] = []
        self._server_endpoints: List[str] = []
        self._role: Optional[Role] = None
        self._current_id = -1
        self._generate = False

    def generate_role(self):
        raise NotImplementedError

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id

    def server_index(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return len(self._worker_endpoints) or 1

    def server_num(self) -> int:
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def role_id(self):
        return self._current_id


class PaddleCloudRoleMaker(RoleMakerBase):
    """reference: role_maker.py:477 — roles from PaddleCloud env vars."""

    def __init__(self, is_collective: bool = False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._generate:
            return
        if self._is_collective:
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            self._worker_endpoints = eps.split(",") if eps else ["127.0.0.1:6170"]
            self._role = Role.WORKER
        else:
            role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
            eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = eps.split(",") if eps else []
            weps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            self._worker_endpoints = weps.split(",") if weps else []
            if role == "TRAINER":
                self._role = Role.WORKER
                self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            else:
                self._role = Role.SERVER
                cur = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
                self._current_id = (
                    self._server_endpoints.index(cur)
                    if cur in self._server_endpoints else 0
                )
        self._generate = True


class TPURoleMaker(RoleMakerBase):
    """TPU-native role maker: one process per host over the JAX
    coordination service (replaces gen_nccl_id TCP rendezvous,
    reference imperative/nccl_context.cc:21-113)."""

    def __init__(self):
        super().__init__()

    def generate_role(self):
        if self._generate:
            return
        import jax

        coord = os.environ.get("PADDLE_COORDINATOR_ADDRESS")
        nproc = int(os.environ.get("PADDLE_NUM_PROCESSES", "1"))
        pid = int(os.environ.get("PADDLE_PROCESS_ID", "0"))
        if coord and nproc > 1:
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=nproc, process_id=pid
            )
        self._current_id = pid
        self._worker_endpoints = [f"proc:{i}" for i in range(nproc)]
        self._role = Role.WORKER
        self._generate = True


class UserDefinedRoleMaker(RoleMakerBase):
    """reference: role_maker.py:988."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = Role(role)
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []

    def generate_role(self):
        self._generate = True

    def worker_num(self):
        return self._worker_num


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    """reference: role_maker.py:1064."""

    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = worker_endpoints or ["127.0.0.1:6170"]
        self._role = Role.WORKER

    def generate_role(self):
        self._generate = True


class MPIRoleMaker(RoleMakerBase):
    """reference: role_maker.py:186 — MPI discovery.  MPI is not part of
    the TPU stack; use TPURoleMaker (coordination service) instead."""

    def __init__(self):
        raise NotImplementedError(
            "MPI role discovery is replaced by TPURoleMaker over the JAX "
            "coordination service"
        )
