from . import role_maker
from . import fleet_base
from .role_maker import (
    Role,
    RoleMakerBase,
    PaddleCloudRoleMaker,
    UserDefinedRoleMaker,
    UserDefinedCollectiveRoleMaker,
    TPURoleMaker,
)
