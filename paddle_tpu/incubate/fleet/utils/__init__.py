"""fleet.utils (reference: incubate/fleet/utils/)."""
