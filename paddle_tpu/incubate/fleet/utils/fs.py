"""Filesystem abstraction for fleet checkpointing.

Reference: python/paddle/fluid/incubate/fleet/utils/fs.py (FS / LocalFS)
and hdfs.py (HDFSClient).  The checkpoint logic is written against this
interface so a remote FS (HDFS/GCS) slots in by implementing the same
methods; LocalFS is the complete local implementation, HDFSClient is a
config-carrying stub that shells out to ``hadoop fs`` when available.
"""
from __future__ import annotations

import abc
import os
import shutil
import subprocess


class FS(abc.ABC):
    @abc.abstractmethod
    def list_dirs(self, fs_path):
        ...

    @abc.abstractmethod
    def ls_dir(self, fs_path):
        ...

    @abc.abstractmethod
    def stat(self, fs_path):
        ...

    @abc.abstractmethod
    def mkdir(self, fs_path):
        ...

    @abc.abstractmethod
    def delete(self, fs_path):
        ...

    @abc.abstractmethod
    def need_upload_download(self):
        ...

    def rmr(self, fs_path):
        return self.delete(fs_path)


class LocalFS(FS):
    """reference: fleet/utils/fs.py LocalFS."""

    def list_dirs(self, fs_path):
        if not self.stat(fs_path):
            return []
        return [d for d in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, d))]

    def ls_dir(self, fs_path):
        return sorted(os.listdir(fs_path)) if self.stat(fs_path) else []

    def stat(self, fs_path):
        return os.path.exists(fs_path)

    def is_exist(self, fs_path):
        return self.stat(fs_path)

    def mkdir(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if not self.stat(fs_path):
            return
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        else:
            os.remove(fs_path)

    def mv(self, src, dst):
        self.delete(dst)
        shutil.move(src, dst)

    def touch(self, fs_path):
        with open(fs_path, "a"):
            pass

    def upload(self, local_path, fs_path):
        self.delete(fs_path)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def need_upload_download(self):
        return False


class HDFSClient(FS):
    """``hadoop fs`` shell-out client (reference: fleet/utils/hdfs.py).
    Requires a hadoop binary; every method degrades to a clear error when
    it is absent, so local runs never silently touch HDFS."""

    def __init__(self, hadoop_home=None, configs=None):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else "hadoop")
        self._configs = configs or {}

    def _run(self, *args, check=False):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=300)
        except FileNotFoundError:
            raise RuntimeError(
                f"hadoop binary not found at {self._hadoop!r}; HDFSClient "
                "needs a hadoop installation") from None
        if check and r.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed (rc={r.returncode}): "
                f"{r.stderr.strip()[:500]}")
        return r

    def list_dirs(self, fs_path):
        r = self._run("-ls", fs_path)
        dirs = []
        for line in r.stdout.splitlines():
            parts = line.split()
            if len(parts) >= 8 and parts[0].startswith("d"):
                dirs.append(os.path.basename(parts[-1]))
        return dirs

    def ls_dir(self, fs_path):
        r = self._run("-ls", fs_path)
        return [os.path.basename(l.split()[-1])
                for l in r.stdout.splitlines() if len(l.split()) >= 8]

    def stat(self, fs_path):
        return self._run("-test", "-e", fs_path).returncode == 0

    def mkdir(self, fs_path):
        self._run("-mkdir", "-p", fs_path, check=True)

    def delete(self, fs_path):
        # -f: deleting a missing path is not an error
        self._run("-rm", "-r", "-f", fs_path)

    def mv(self, src, dst):
        self._run("-mv", src, dst, check=True)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path, check=True)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path, check=True)

    def need_upload_download(self):
        return True
