"""Fleet parameter-server mode (transpiler-based).

Reference: python/paddle/fluid/incubate/fleet/parameter_server/
distribute_transpiler/__init__.py — fleet facade over
DistributeTranspiler: init_worker/init_server/run_server +
ParameterServerOptimizer.  TPU-native: the pserver is the C++ table
service (distributed_ps/), trainers talk to it through host ops on the
executor's hybrid path; dense tables apply the optimizer server-side
(configured from the stripped optimize ops, like pslib downpour tables).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ....framework.core import default_main_program, default_startup_program
from ....transpiler.distribute_transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from ..base.fleet_base import DistributedOptimizer, Fleet, Mode


def _optimizer_cfg_from_ops(opt_ops, param_name, lr_value) -> dict:
    for op_ in opt_ops:
        rv = op_.attr("op_role_var")
        if rv and rv[0] == param_name:
            t = op_.type
            if t == "sgd":
                return {"optimizer": "sgd", "lr": lr_value}
            if t == "momentum":
                return {"optimizer": "momentum", "lr": lr_value,
                        "mu": op_.attr("mu", 0.9)}
            if t == "adam":
                return {"optimizer": "adam", "lr": lr_value,
                        "beta1": op_.attr("beta1", 0.9),
                        "beta2": op_.attr("beta2", 0.999),
                        "eps": op_.attr("epsilon", 1e-8)}
            if t == "adagrad":
                return {"optimizer": "adagrad", "lr": lr_value,
                        "eps": op_.attr("epsilon", 1e-6)}
    return {"optimizer": "sgd", "lr": lr_value}


class FleetTranspiler(Fleet):
    """reference: parameter_server/distribute_transpiler/__init__.py."""

    def __init__(self):
        super().__init__(Mode.TRANSPILER)
        self._transpiler: Optional[DistributeTranspiler] = None
        self._origin_lr = 0.01
        self.main_program = None
        self.startup_program = None
        self._servers = []
        self._client = None

    # ------------------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._origin_lr = float(getattr(optimizer, "_learning_rate", 0.01)) \
            if not callable(getattr(optimizer, "_learning_rate", None)) else 0.01
        self._optimizer = ParameterServerOptimizer(optimizer, strategy, self)
        return self._optimizer

    # ------------------------------------------------------------------
    def init_worker(self):
        """Connect the PS client; trainer 0 pushes initial params."""
        from ....distributed_ps import runtime
        from ....distributed_ps.service import PSClient

        eps = self.server_endpoints()
        self._client = PSClient(eps)
        runtime.set_client(self._client, self.worker_index(),
                           heartbeat_interval=5.0)
        t = self._transpiler
        # create tables on servers
        block = t.origin_program.global_block()
        for p, g in t._param_grads:
            var = block._find_var_recursive(p)
            size = int(np.prod([abs(s) for s in var.shape]))
            cfg = _optimizer_cfg_from_ops(t._opt_ops, p, self._origin_lr)
            self._client.create_dense(p, size, **cfg)
        # sparse embedding tables (downpour-style: rows materialize on
        # first pull server-side; no trainer init push)
        for tname, dim in getattr(t, "_sparse_tables", {}).items():
            cfg = _optimizer_cfg_from_ops(t._opt_ops, tname, self._origin_lr)
            self._client.create_sparse(tname, dim, **cfg)
        if self.worker_index() == 0:
            # push locally-initialized params (reference: trainer0 bcast)
            from ....framework.scope import global_scope

            scope = global_scope()
            for p, g in t._param_grads:
                val = scope.get(p)
                if val is not None:
                    self._client.init_dense(p, np.asarray(val).ravel())

        # install the communicator for async / half-async / GEO modes
        # (reference: Communicator::InitInstance + fleet init_worker)
        from ....transpiler.distribute_transpiler import DistributedMode
        from ....distributed_ps.communicator import (
            AsyncCommunicator, GeoSgdCommunicator, HalfAsyncCommunicator)

        mode = getattr(t, "mode", DistributedMode.SYNC)
        if mode == DistributedMode.ASYNC:
            runtime.set_communicator(
                AsyncCommunicator(self._client).start())
        elif mode == DistributedMode.HALF_ASYNC:
            runtime.set_communicator(
                HalfAsyncCommunicator(self._client).start())
        elif mode == DistributedMode.GEO:
            comm = GeoSgdCommunicator(
                self._client, [p for p, _ in t._param_grads],
                push_nums=getattr(t.config, "geo_sgd_need_push_nums", 100),
                sparse_tables=getattr(t, "_sparse_tables", {}))
            # baseline snapshots = the just-initialized params (what the
            # server holds after trainer-0's init push); start() then
            # pulls baselines for any param missing from the scope
            from ....framework.scope import global_scope
            comm.init_snapshots(global_scope())
            runtime.set_communicator(comm.start())

    def init_server(self, model_dir=None, endpoint=None):
        from ....distributed_ps.service import PSServer

        ep = endpoint or self.server_endpoints()[self.server_index()]
        server = PSServer(ep, n_trainers=self.worker_num())
        self._servers.append(server)
        if model_dir:
            server._load(model_dir)
        return server

    def run_server(self, block=False):
        for s in self._servers:
            s.start(block=block)
        return self._servers

    def stop_worker(self):
        from ....distributed_ps import runtime

        runtime.clear()
        if self._client is not None:
            self._client.close()

    def save_persistables(self, executor=None, dirname="./ps_model",
                          main_program=None):
        self._client.save(dirname)

    def load_persistables(self, executor=None, dirname="./ps_model"):
        self._client.load(dirname)


fleet = FleetTranspiler()


class ParameterServerOptimizer(DistributedOptimizer):
    def __init__(self, optimizer, strategy=None, fleet_=None):
        super().__init__(optimizer,
                         strategy or DistributeTranspilerConfig())
        self._fleet = fleet_

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        optimize_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        f = self._fleet
        config = self._strategy if isinstance(
            self._strategy, DistributeTranspilerConfig) else DistributeTranspilerConfig()
        t = DistributeTranspiler(config)
        sync = getattr(config, "sync_mode", True)
        t.transpile(
            trainer_id=f.worker_index() if f._is_initialized else 0,
            program=loss.block.program,
            pservers=",".join(f.server_endpoints()) if f._is_initialized
            else "127.0.0.1:6174",
            trainers=f.worker_num() if f._is_initialized else 1,
            sync_mode=sync,
            mode=config.distributed_mode,
        )
        f._transpiler = t
        f.main_program = t.origin_program
        f.startup_program = startup_program or default_startup_program()
        return optimize_ops, params_grads
