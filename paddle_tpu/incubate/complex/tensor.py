"""Complex tensor ops — reference:
python/paddle/incubate/complex/tensor/{math,linalg,manipulation}.py.

Each op is the textbook complex decomposition over the package's REAL
ops, so the whole family traces/differentiates through the standard
registry (dygraph and static alike).  Real operands broadcast in as
(x, 0i), matching the reference's mixed real/complex support.
"""
from __future__ import annotations

from ... import layers as F
from ... import tensor as pt_tensor
from .helper import complex_variable_exists, is_complex
from .variable import ComplexVariable

__all__ = ["elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "kron", "trace", "sum", "matmul",
           "reshape", "transpose"]


def _parts(x):
    if is_complex(x):
        return x.real, x.imag
    return x, None


def _add(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return F.elementwise_add(a, b)


def _sub(a, b):
    if b is None:
        return a
    if a is None:
        return F.scale(b, -1.0)
    return F.elementwise_sub(a, b)


def elementwise_add(x, y, axis=-1, name=None):
    complex_variable_exists([x, y], "elementwise_add")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    return ComplexVariable(F.elementwise_add(xr, yr, axis=axis),
                           _add(xi, yi))


def elementwise_sub(x, y, axis=-1, name=None):
    complex_variable_exists([x, y], "elementwise_sub")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    return ComplexVariable(F.elementwise_sub(xr, yr, axis=axis),
                           _sub(xi, yi))


def elementwise_mul(x, y, axis=-1, name=None):
    complex_variable_exists([x, y], "elementwise_mul")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    # (a+bi)(c+di) = (ac-bd) + (ad+bc)i
    real = F.elementwise_mul(xr, yr, axis=axis)
    if xi is not None and yi is not None:
        real = F.elementwise_sub(real, F.elementwise_mul(xi, yi, axis=axis))
    imag = None
    if yi is not None:
        imag = F.elementwise_mul(xr, yi, axis=axis)
    if xi is not None:
        imag = _add(imag, F.elementwise_mul(xi, yr, axis=axis))
    return ComplexVariable(real, imag)


def elementwise_div(x, y, axis=-1, name=None):
    complex_variable_exists([x, y], "elementwise_div")
    yr, yi = _parts(y)
    if yi is None:
        xr, xi = _parts(x)
        return ComplexVariable(F.elementwise_div(xr, yr, axis=axis),
                               F.elementwise_div(xi, yr, axis=axis))
    # x / y = x * conj(y) / |y|^2
    denom = _add(F.elementwise_mul(yr, yr),
                 F.elementwise_mul(yi, yi))
    num = elementwise_mul(x, ComplexVariable(yr, F.scale(yi, -1.0)),
                          axis=axis)
    return ComplexVariable(F.elementwise_div(num.real, denom, axis=axis),
                           F.elementwise_div(num.imag, denom, axis=axis))


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    complex_variable_exists([x, y], "matmul")
    xr, xi = _parts(x)
    yr, yi = _parts(y)

    def mm(a, b):
        return F.matmul(a, b, transpose_x=transpose_x,
                        transpose_y=transpose_y, alpha=alpha)

    real = mm(xr, yr)
    if xi is not None and yi is not None:
        real = F.elementwise_sub(real, mm(xi, yi))
    imag = None
    if yi is not None:
        imag = mm(xr, yi)
    if xi is not None:
        imag = _add(imag, mm(xi, yr))
    return ComplexVariable(real, imag)


def kron(x, y, name=None):
    complex_variable_exists([x, y], "kron")
    xr, xi = _parts(x)
    yr, yi = _parts(y)
    real = pt_tensor.kron(xr, yr)
    if xi is not None and yi is not None:
        real = F.elementwise_sub(real, pt_tensor.kron(xi, yi))
    imag = None
    if yi is not None:
        imag = pt_tensor.kron(xr, yi)
    if xi is not None:
        imag = _add(imag, pt_tensor.kron(xi, yr))
    return ComplexVariable(real, imag)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    complex_variable_exists([x], "trace")
    return ComplexVariable(
        pt_tensor.trace(x.real, offset=offset, axis1=axis1, axis2=axis2),
        pt_tensor.trace(x.imag, offset=offset, axis1=axis1, axis2=axis2))


def sum(input, dim=None, keep_dim=False, name=None):
    complex_variable_exists([input], "sum")
    return ComplexVariable(
        F.reduce_sum(input.real, dim=dim, keep_dim=keep_dim),
        F.reduce_sum(input.imag, dim=dim, keep_dim=keep_dim))


def reshape(x, shape, inplace=False, name=None):
    complex_variable_exists([x], "reshape")
    return ComplexVariable(F.reshape(x.real, shape),
                           F.reshape(x.imag, shape))


def transpose(x, perm, name=None):
    complex_variable_exists([x], "transpose")
    return ComplexVariable(F.transpose(x.real, perm),
                           F.transpose(x.imag, perm))
