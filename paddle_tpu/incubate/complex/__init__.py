"""paddle.complex — complex-tensor preview namespace.

Reference: python/paddle/incubate/complex/ (helper.py is_complex +
tensor/{math,linalg,manipulation}.py) over fluid.framework
ComplexVariable (framework.py:1691) — a (real, imag) pair of Variables.

TPU-first note: jax/XLA support complex dtypes natively, but the
reference API contract is the (real, imag) pair with these ten
functions, so the ops here are compositions of the package's real ops —
they trace through the same registry in both dygraph and static mode
(and therefore jit/grad like everything else).
"""
from __future__ import annotations

from . import tensor
from .tensor import (elementwise_add, elementwise_div, elementwise_mul,
                     elementwise_sub, kron, matmul, reshape, sum, trace,
                     transpose)
from .helper import is_complex
from .variable import ComplexVariable

__all__ = ["ComplexVariable", "is_complex", "tensor",
           "elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "kron", "trace", "sum", "matmul",
           "reshape", "transpose"]
