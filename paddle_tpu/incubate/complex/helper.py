"""Reference: python/paddle/incubate/complex/helper.py."""
from __future__ import annotations

from .variable import ComplexVariable


def is_complex(x) -> bool:
    return isinstance(x, ComplexVariable)


def is_real(x) -> bool:
    return not isinstance(x, ComplexVariable)


def complex_variable_exists(inputs, layer_name):
    if any(is_complex(x) for x in inputs):
        return
    raise ValueError(
        f"{layer_name} expects at least one ComplexVariable input")
