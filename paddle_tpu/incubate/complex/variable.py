"""ComplexVariable — a (real, imag) pair of framework variables.

Reference: fluid/framework.py:1691 ComplexVariable.
"""
from __future__ import annotations

import numpy as np


class ComplexVariable:
    def __init__(self, real, imag):
        self.real = real
        self.imag = imag

    @property
    def shape(self):
        return self.real.shape

    @property
    def dtype(self):
        return self.real.dtype

    def numpy(self):
        return np.asarray(self.real.numpy()) + 1j * np.asarray(
            self.imag.numpy())

    def __repr__(self):
        return f"ComplexVariable(real={self.real!r}, imag={self.imag!r})"

    __str__ = __repr__
