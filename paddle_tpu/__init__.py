"""paddle_tpu: a TPU-native deep-learning framework with the capabilities
of PaddlePaddle (Fluid era).  See SURVEY.md for the blueprint.

Two API surfaces, mirroring the reference:
* ``paddle_tpu.fluid`` — the Fluid static-graph + dygraph API
  (reference: python/paddle/fluid/).
* top-level 2.0-preview style aliases (reference: python/paddle/).
"""
import os as _os

# Persistent XLA compilation cache: compiles through the TPU tunnel are
# expensive (~30s+ per conv-grad subgraph); cache them across processes.
try:  # pragma: no cover
    import jax as _jax

    _cache_dir = _os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", "/root/.cache/paddle_tpu_xla"
    )
    _os.makedirs(_cache_dir, exist_ok=True)
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

from . import framework
from .framework import (
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    Program,
    Variable,
    program_guard,
    default_main_program,
    default_startup_program,
    in_dygraph_mode,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from . import ops
from . import inference
from . import tensor
from . import nn
from . import metric
from . import distribution
from . import static
from . import incubate
from .incubate import complex  # noqa: A004  (paddle.complex preview API)
import sys as _sys

# make `import paddle_tpu.complex` work as a module path too, not just
# attribute access (users import it both ways)
_sys.modules[__name__ + ".complex"] = complex
from .tensor import (
    to_tensor, full, full_like, zeros, ones, zeros_like, ones_like,
    arange, linspace, matmul, concat, reshape, transpose, stack, split,
    squeeze, unsqueeze, flatten, cast, add, subtract, multiply, divide,
    maximum, minimum, clip, rand, randn, randint, uniform, normal,
    argmax, argmin, topk, where, tile, expand, flip, roll, gather,
    allclose, equal_all, bmm, dot, norm, tril, triu, numel,
)
from .executor import Executor
from .utils.memory import memory_stats, memory_summary
from .backward import append_backward, gradients
from .framework.scope import global_scope, scope_guard, LoDTensor, Scope


def grad(*args, **kwargs):
    """``paddle.grad`` — eager partial grad (PartialGradEngine analog);
    see dygraph.base.grad."""
    from .dygraph.base import grad as _g

    return _g(*args, **kwargs)


def enable_dygraph(place=None):
    from .dygraph.base import enable_dygraph as _e

    return _e(place)


def disable_dygraph():
    from .dygraph.base import disable_dygraph as _d

    return _d()


__version__ = "0.1.0"
