"""paddle_tpu: a TPU-native deep-learning framework with the capabilities
of PaddlePaddle (Fluid era).  See SURVEY.md for the blueprint.

Two API surfaces, mirroring the reference:
* ``paddle_tpu.fluid`` — the Fluid static-graph + dygraph API
  (reference: python/paddle/fluid/).
* top-level 2.0-preview style aliases (reference: python/paddle/).
"""
from . import framework
from .framework import (
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    Program,
    Variable,
    program_guard,
    default_main_program,
    default_startup_program,
    in_dygraph_mode,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from . import ops
from .executor import Executor
from .backward import append_backward, gradients
from .framework.scope import global_scope, scope_guard, LoDTensor, Scope

__version__ = "0.1.0"
