"""fluid.layers long tail: vision ops, structured losses, misc utilities.

Reference: python/paddle/fluid/layers/nn.py (the ~150 functions beyond the
core set in layers/nn.py), layers/loss.py, layers/control_flow.py (Print/
Assert), layers/io.py (double_buffer), layers/ops.py (activation wrappers).
Each function builds vars + ops via LayerHelper; the lowerings live in
ops/vision_ops.py, ops/loss_ops.py, ops/sequence_ops.py.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Variable, in_dygraph_mode
from ..framework.dtype import VarType, convert_dtype
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer, NormalInitializer


def _simple(op_type, out_slots=("Out",), **fixed):
    """Build a LayerHelper wrapper for an op with X->Out shape."""

    def fn(x, *, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        a = dict(fixed)
        a.update(attrs)
        outs = {s: [helper.create_variable_for_type_inference(x.dtype)]
                for s in out_slots}
        helper.append_op(op_type, inputs={"X": [x]}, outputs=outs, attrs=a)
        ret = [outs[s][0] for s in out_slots]
        return ret[0] if len(ret) == 1 else tuple(ret)

    fn.__name__ = op_type
    return fn


# --------------------------------------------------------------------------
# activation wrappers over existing ops (reference: layers/ops.py)
# --------------------------------------------------------------------------
def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple("brelu")(x, name=name, t_min=t_min, t_max=t_max)


def soft_relu(x, threshold=40.0, name=None):
    return _simple("soft_relu")(x, name=name, threshold=threshold)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _simple("stanh")(x, name=name, scale_a=scale_a, scale_b=scale_b)


def elu(x, alpha=1.0, name=None):
    return _simple("elu")(x, name=name, alpha=alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _simple("selu")(x, name=name, scale=scale, alpha=alpha)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _simple("hard_sigmoid")(x, name=name, slope=slope, offset=offset)


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(factor, Variable):
        inputs["FactorTensor"] = [factor]
    else:
        attrs["factor"] = float(factor)
    helper.append_op("pow", inputs=inputs, outputs={"Out": [out]}, attrs=attrs)
    return out


# --------------------------------------------------------------------------
# logical / comparison wrappers (reference: layers/control_flow.py)
# --------------------------------------------------------------------------
def _binary(op_type):
    def fn(x, y, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference(VarType.BOOL)
        helper.append_op(op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]})
        return out

    fn.__name__ = op_type
    return fn


logical_and = _binary("logical_and")
logical_or = _binary("logical_or")
logical_xor = _binary("logical_xor")
not_equal = _binary("not_equal")
less_equal = _binary("less_equal")
greater_than = _binary("greater_than")
greater_equal = _binary("greater_equal")


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("logical_not", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def isfinite(x, name=None):
    helper = LayerHelper("isfinite", name=name)
    out = helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_inf(x, name=None):
    helper = LayerHelper("isinf", name=name)
    out = helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("isinf", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_nan(x, name=None):
    helper = LayerHelper("isnan", name=name)
    out = helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("isnan", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


# --------------------------------------------------------------------------
# vision layers (reference: layers/nn.py)
# --------------------------------------------------------------------------
def pixel_shuffle(x, upscale_factor, name=None):
    return _simple("pixel_shuffle")(x, name=name, upscale_factor=upscale_factor)


def space_to_depth(x, blocksize, name=None):
    return _simple("space_to_depth")(x, name=name, blocksize=blocksize)


def shuffle_channel(x, group, name=None):
    return _simple("shuffle_channel")(x, name=name, group=group)


def maxout(x, groups, name=None, axis=1):
    return _simple("maxout")(x, name=name, groups=groups, axis=axis)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op("lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    helper = LayerHelper("affine_channel", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]},
                     attrs={"data_layout": data_layout})
    return helper.append_activation(out, act)


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(shape, Variable):
        inputs["Y"] = [shape]
    else:
        attrs["shape"] = list(shape)
    if offsets is not None:
        if isinstance(offsets, Variable):
            inputs["Offsets"] = [offsets]
        else:
            attrs["offsets"] = list(offsets)
    helper.append_op("crop", inputs=inputs, outputs={"Out": [out]}, attrs=attrs)
    return out


def crop_tensor(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop_tensor", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(shape, Variable):
        inputs["Shape"] = [shape]
    elif shape is not None:
        attrs["shape"] = list(shape)
    if offsets is not None:
        if isinstance(offsets, Variable):
            inputs["Offsets"] = [offsets]
        else:
            attrs["offsets"] = list(offsets)
    helper.append_op("crop_tensor", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op("pad_constant_like", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"pad_value": float(pad_value)})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)
    if isinstance(kernel_sizes, int):
        kernel_sizes = [kernel_sizes, kernel_sizes]
    if isinstance(strides, int):
        strides = [strides, strides]
    if isinstance(dilations, int):
        dilations = [dilations, dilations]
    if isinstance(paddings, int):
        paddings = [paddings] * 4
    elif len(paddings) == 2:
        paddings = paddings * 2
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("unfold", inputs={"X": [x]}, outputs={"Y": [out]},
                     attrs={"kernel_sizes": kernel_sizes, "strides": strides,
                            "paddings": paddings, "dilations": dilations})
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=1, deformable_groups=1,
                    im2col_step=1, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """reference: layers/nn.py deformable_conv (DCN v1/v2)."""
    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    ksize = [filter_size, filter_size] if isinstance(filter_size, int) else list(filter_size)
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
    num_channels = input.shape[1]
    filter_shape = [num_filters, num_channels // groups] + ksize
    filt = helper.create_parameter(param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    op_type = "deformable_conv" if modulated else "deformable_conv_v1"
    inputs = {"Input": [input], "Offset": [offset], "Filter": [filt]}
    if modulated:
        inputs["Mask"] = [mask]
    helper.append_op(op_type, inputs=inputs, outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups,
                            "deformable_groups": deformable_groups,
                            "im2col_step": im2col_step})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2,
                                    bias_attr=bias_attr)
    return pre_act


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=[1, 1],
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           name=None):
    helper = LayerHelper("deformable_roi_pooling", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    top = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op("deformable_roi_pooling",
                     inputs={"Input": [input], "ROIs": [rois], "Trans": [trans]},
                     outputs={"Output": [out], "TopCount": [top]},
                     attrs={"no_trans": no_trans, "spatial_scale": spatial_scale,
                            "group_size": group_size, "pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "part_size": part_size or [pooled_height, pooled_width],
                            "sample_per_part": sample_per_part,
                            "trans_std": trans_std,
                            "position_sensitive": position_sensitive})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: layers/nn.py spectral_norm; U/V persist across steps via
    UOut/VOut rebinding onto the same vars."""
    helper = LayerHelper("spectral_norm", name=name)
    dtype = weight.dtype
    h = weight.shape[dim]
    w = int(np.prod([s for i, s in enumerate(weight.shape) if i != dim]))
    u = helper.create_parameter(attr=None, shape=[h], dtype=dtype,
                                default_initializer=NormalInitializer(0.0, 1.0))
    v = helper.create_parameter(attr=None, shape=[w], dtype=dtype,
                                default_initializer=NormalInitializer(0.0, 1.0))
    u.stop_gradient = True
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("spectral_norm",
                     inputs={"Weight": [weight], "U": [u], "V": [v]},
                     outputs={"Out": [out], "UOut": [u], "VOut": [v]},
                     attrs={"dim": dim, "power_iters": power_iters, "eps": eps})
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999):
    """reference: layers/nn.py data_norm."""
    helper = LayerHelper("data_norm", name=name)
    dtype = input.dtype
    c = input.shape[1]
    batch_size = helper.create_parameter(
        attr=None, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1e4))
    batch_sum = helper.create_parameter(
        attr=None, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(0.0))
    batch_square_sum = helper.create_parameter(
        attr=None, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1e4))
    means = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    scales = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("data_norm",
                     inputs={"X": [input], "BatchSize": [batch_size],
                             "BatchSum": [batch_sum],
                             "BatchSquareSum": [batch_square_sum]},
                     outputs={"Y": [out], "Means": [means], "Scales": [scales]},
                     attrs={"epsilon": epsilon})
    return helper.append_activation(out, act)


def affine_grid(theta, out_shape, name=None, align_corners=True):
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": [theta]}
    attrs = {"align_corners": align_corners}
    if isinstance(out_shape, Variable):
        inputs["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = list(out_shape)
    helper.append_op("affine_grid", inputs=inputs, outputs={"Output": [out]},
                     attrs=attrs)
    return out


def grid_sampler(x, grid, name=None, mode="bilinear", padding_mode="zeros",
                 align_corners=True):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("grid_sampler", inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]},
                     attrs={"mode": mode, "padding_mode": padding_mode,
                            "align_corners": align_corners})
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _simple("temporal_shift")(x, name=name, seg_num=seg_num,
                                     shift_ratio=shift_ratio)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True, data_format="NCDHW"):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ksize = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    strides = [pool_stride] * 3 if isinstance(pool_stride, int) else list(pool_stride)
    pads = [pool_padding] * 3 if isinstance(pool_padding, int) else list(pool_padding)
    helper.append_op("pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type, "ksize": ksize,
                            "strides": strides, "paddings": pads,
                            "global_pooling": global_pooling,
                            "exclusive": exclusive, "ceil_mode": ceil_mode})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    helper = LayerHelper("adaptive_pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ksize = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    helper.append_op("adaptive_pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type, "ksize": ksize,
                            "adaptive": True})
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    ksize = [filter_size] * 3 if isinstance(filter_size, int) else list(filter_size)
    stride = [stride] * 3 if isinstance(stride, int) else list(stride)
    padding = [padding] * 3 if isinstance(padding, int) else list(padding)
    dilation = [dilation] * 3 if isinstance(dilation, int) else list(dilation)
    num_channels = input.shape[1]
    filt = helper.create_parameter(
        param_attr, shape=[num_filters, num_channels // groups] + ksize,
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv3d", inputs={"Input": [input], "Filter": [filt]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2,
                                    bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                     name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    ksize = [filter_size] * 3 if isinstance(filter_size, int) else list(filter_size)
    stride = [stride] * 3 if isinstance(stride, int) else list(stride)
    padding = [padding] * 3 if isinstance(padding, int) else list(padding)
    dilation = [dilation] * 3 if isinstance(dilation, int) else list(dilation)
    num_channels = input.shape[1]
    filt = helper.create_parameter(
        param_attr, shape=[num_channels, num_filters // groups] + ksize,
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv3d_transpose",
                     inputs={"Input": [input], "Filter": [filt]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2,
                                    bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def _interp_layer(op_type, input, out_shape, scale, align_corners, name,
                  ndims):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners}
    keys = ["out_d", "out_h", "out_w"][-ndims:]
    if out_shape is not None:
        for k, v in zip(keys, out_shape):
            attrs[k] = int(v)
    elif scale is not None:
        spatial = input.shape[-ndims:]
        for k, s in zip(keys, spatial):
            attrs[k] = int(s * scale)
    helper.append_op(op_type, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def resize_linear(input, out_shape=None, scale=None, name=None,
                  align_corners=True, align_mode=1, data_format="NCW"):
    return _interp_layer("linear_interp", input, out_shape, scale,
                         align_corners, name, 1)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return _interp_layer("trilinear_interp", input, out_shape, scale,
                         align_corners, name, 3)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    """reference: layers/nn.py image_resize dispatcher."""
    op_map = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
              "BICUBIC": "bicubic_interp", "TRILINEAR": "trilinear_interp",
              "LINEAR": "linear_interp"}
    op_type = op_map[resample.upper()]
    nd = 3 if op_type == "trilinear_interp" else (1 if op_type == "linear_interp" else 2)
    return _interp_layer(op_type, input, out_shape, scale, align_corners,
                         name, nd)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len (reference:
    layers/nn.py image_resize_short)."""
    in_shape = input.shape
    hw = in_shape[2:4]
    short_idx = hw.index(min(hw))
    out_shape = list(hw)
    out_shape[short_idx] = out_short_len
    out_shape[1 - short_idx] = int(
        float(out_shape[1 - short_idx])
        * (float(out_short_len) / float(hw[short_idx])) + 0.5)
    return image_resize(input=input, out_shape=out_shape, resample=resample)


def random_crop(x, shape, seed=None):
    """reference: layers/nn.py random_crop — train-time random crop; the
    offsets come from the threaded program rng (jit-safe dynamic_slice)."""
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("random_crop", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape), "seed": seed or 0})
    return out


# --------------------------------------------------------------------------
# matrix / embedding-adjacent layers
# --------------------------------------------------------------------------
def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(param_attr,
                                shape=[size, x.shape[1], y.shape[1]],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        bias = helper.create_parameter(bias_attr, shape=[1, size], dtype=dtype,
                                       is_bias=True)
        inputs["Bias"] = [bias]
    helper.append_op("bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out, act)


def fsp_matrix(x, y):
    helper = LayerHelper("fsp")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fsp", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return out


def add_position_encoding(input, alpha, beta, name=None):
    return _simple("add_position_encoding")(input, name=name, alpha=alpha,
                                            beta=beta)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op("multiplex", inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def unbind(input, axis=0):
    helper = LayerHelper("unbind")
    n = input.shape[axis]
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op("unbind", inputs={"X": [input]}, outputs={"Out": outs},
                     attrs={"axis": axis})
    return outs


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _simple("shard_index")(input, index_num=index_num, nshards=nshards,
                                  shard_id=shard_id, ignore_value=ignore_value)


def hash(input, hash_size, num_hash=1, name=None):
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("hash", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"num_hash": num_hash, "mod_by": hash_size})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("sampling_id", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"seed": seed})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op("gaussian_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx, "mean": mean,
                            "std": std, "seed": seed,
                            "dtype": int(convert_dtype(dtype))})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op("uniform_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx, "min": min,
                            "max": max, "seed": seed,
                            "dtype": int(convert_dtype(dtype))})
    return out


def similarity_focus(input, axis, indexes, name=None):
    return _simple("similarity_focus")(input, name=name, axis=axis,
                                       indexes=list(indexes))


def get_tensor_from_selected_rows(x, name=None):
    return _simple("get_tensor_from_selected_rows")(x, name=name)


def merge_selected_rows(x, name=None):
    return _simple("merge_selected_rows")(x, name=name)


def unique(x, dtype="int32"):
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op("unique", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index]},
                     attrs={"dtype": int(convert_dtype(dtype))})
    return out, index


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(convert_dtype(dtype))
    count = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op("unique_with_counts", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index], "Count": [count]},
                     attrs={"dtype": int(convert_dtype(dtype))})
    return out, index, count


def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", name=name)
    out = helper.create_variable_for_type_inference(ref.dtype)
    helper.append_op("scatter_nd_add",
                     inputs={"X": [ref], "Index": [index], "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def scatter_nd(index, updates, shape, name=None):
    """scatter_nd(i, u, s) == scatter_nd_add(zeros(s), i, u) (reference:
    layers/nn.py scatter_nd)."""
    from .tensor import fill_constant
    zero = fill_constant(shape, updates.dtype, 0.0)
    return scatter_nd_add(zero, index, updates, name)


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("strided_slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends), "strides": list(strides)})
    return out


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand_as",
                     inputs={"X": [x], "target_tensor": [target_tensor]},
                     outputs={"Out": [out]})
    return out


def size(input):
    helper = LayerHelper("size")
    out = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("size", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def rank(input):
    """Static rank as a filled constant (reference: layers/nn.py rank)."""
    from .tensor import fill_constant
    return fill_constant(shape=[1], dtype="int32", value=len(input.shape))


def sum(x):
    helper = LayerHelper("sum")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("sum", inputs={"X": list(xs)}, outputs={"Out": [out]})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference(VarType.FP32)
    wrong = helper.create_variable_for_type_inference(VarType.INT32)
    correct = helper.create_variable_for_type_inference(VarType.INT32)
    helper.append_op("mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                              "OutCorrect": [correct]},
                     attrs={"num_classes": num_classes})
    return miou, wrong, correct


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    c = input.shape[1]
    scale = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("group_norm",
                     inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    c = input.shape[1]
    scale = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    smean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    svar = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("instance_norm",
                     inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
                     outputs={"Y": [out], "SavedMean": [smean],
                              "SavedVariance": [svar]},
                     attrs={"epsilon": epsilon})
    return out


def inplace_abn(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
                param_attr=None, bias_attr=None, data_layout="NCHW", name=None,
                moving_mean_name=None, moving_variance_name=None,
                do_model_average_for_mean_and_var=True, use_global_stats=False,
                act_alpha=1.0):
    """In-place activated batch norm — functionally batch_norm + act
    (in-place-ness is an XLA buffer-donation concern, not a graph one)."""
    from .nn import batch_norm
    return batch_norm(input, act=act, is_test=is_test, momentum=momentum,
                      epsilon=epsilon, param_attr=param_attr,
                      bias_attr=bias_attr, data_layout=data_layout, name=name,
                      moving_mean_name=moving_mean_name,
                      moving_variance_name=moving_variance_name,
                      use_global_stats=use_global_stats)


# --------------------------------------------------------------------------
# structured losses
# --------------------------------------------------------------------------
def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """reference: layers/nn.py warpctc.  With input_length given, input is
    padded time-major (Tmax, B, C); labels padded (B, Lmax)."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(input.dtype,
                                                     stop_gradient=True)
    inputs = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length]
    if label_length is not None:
        inputs["LabelLength"] = [label_length]
    helper.append_op("warpctc", inputs=inputs,
                     outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """argmax + merge-repeats + drop-blank (reference: layers/nn.py
    ctc_greedy_decoder = topk + ctc_align)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    # argmax over classes
    idx = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("arg_max", inputs={"X": [input]}, outputs={"Out": [idx]},
                     attrs={"axis": -1})
    out = helper.create_variable_for_type_inference(VarType.INT64)
    out_len = helper.create_variable_for_type_inference(VarType.INT64)
    inputs = {"Input": [idx]}
    if input_length is not None:
        inputs["InputLength"] = [input_length]
    helper.append_op("ctc_align", inputs=inputs,
                     outputs={"Output": [out], "OutputLength": [out_len]},
                     attrs={"blank": blank, "padding_value": padding_value})
    if input_length is None:
        return out
    return out, out_len


def linear_chain_crf(input, label, param_attr=None, length=None):
    """reference: layers/nn.py linear_chain_crf.  Padded emission
    (B, T, D) + length (B,)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(param_attr, shape=[size + 2, size],
                                         dtype=input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype,
                                                      stop_gradient=True)
    eexps = helper.create_variable_for_type_inference(input.dtype,
                                                      stop_gradient=True)
    texps = helper.create_variable_for_type_inference(input.dtype,
                                                      stop_gradient=True)
    ll = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": [input], "Transition": [transition],
              "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op("linear_chain_crf", inputs=inputs,
                     outputs={"Alpha": [alpha], "EmissionExps": [eexps],
                              "TransitionExps": [texps],
                              "LogLikelihood": [ll]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    transition = helper.main_program.global_block()._find_var_recursive(
        param_attr if isinstance(param_attr, str) else param_attr.name
    ) if param_attr is not None and not isinstance(param_attr, Variable) else param_attr
    if transition is None:
        raise ValueError("crf_decoding needs the transition parameter "
                         "created by linear_chain_crf (pass its ParamAttr)")
    path = helper.create_variable_for_type_inference(VarType.INT64)
    inputs = {"Emission": [input], "Transition": [transition]}
    outputs = {"ViterbiPath": [path]}
    if label is not None:
        inputs["Label"] = [label]
        correct = helper.create_variable_for_type_inference(VarType.INT64)
        outputs["Correct"] = [correct]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op("crf_decoding", inputs=inputs, outputs=outputs)
    if label is not None:
        return outputs["Correct"][0]
    return path


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dtype = input.dtype
    dim = input.shape[1]
    num_neg_samples = num_neg_samples or 10
    w = helper.create_parameter(param_attr, shape=[num_total_classes, dim],
                                dtype=dtype)
    cost = helper.create_variable_for_type_inference(dtype)
    slogits = helper.create_variable_for_type_inference(dtype,
                                                        stop_gradient=True)
    slabels = helper.create_variable_for_type_inference(VarType.INT64,
                                                        stop_gradient=True)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_total_classes, 1],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    sampler_id = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}[sampler]
    helper.append_op("nce", inputs=inputs,
                     outputs={"Cost": [cost], "SampleLogits": [slogits],
                              "SampleLabels": [slabels]},
                     attrs={"num_total_classes": num_total_classes,
                            "num_neg_samples": num_neg_samples,
                            "sampler": sampler_id, "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    helper = LayerHelper("hierarchical_sigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    dim = input.shape[1]
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, dim],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    pre_out = helper.create_variable_for_type_inference(dtype,
                                                        stop_gradient=True)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if is_custom or path_table is not None:
        if path_table is None or path_code is None:
            raise ValueError("hsigmoid custom tree needs both path_table "
                             "and path_code")
        inputs["PathTable"] = [path_table]
        inputs["PathCode"] = [path_code]
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_classes - 1, 1],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    helper.append_op("hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [out], "PreOut": [pre_out]},
                     attrs={"num_classes": num_classes})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("bpr_loss", inputs={"X": [input], "Label": [label]},
                     outputs={"Out": [out]})
    return out


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    helper = LayerHelper("center_loss", param_attr=param_attr)
    dtype = input.dtype
    centers = helper.create_parameter(param_attr,
                                      shape=[num_classes, input.shape[1]],
                                      dtype=dtype)
    centers.stop_gradient = True
    from .tensor import fill_constant
    if isinstance(alpha, Variable):
        alpha_var = alpha
    else:
        alpha_var = fill_constant(shape=[1], dtype=dtype, value=float(alpha))
    loss = helper.create_variable_for_type_inference(dtype)
    diff = helper.create_variable_for_type_inference(dtype,
                                                     stop_gradient=True)
    helper.append_op("center_loss",
                     inputs={"X": [input], "Label": [label],
                             "Centers": [centers],
                             "CenterUpdateRate": [alpha_var]},
                     outputs={"Loss": [loss], "SampleCenterDiff": [diff],
                              "CentersOut": [centers]},
                     attrs={"need_update": update_center})
    return loss


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype,
                                                    stop_gradient=True)
    helper.append_op("margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": margin})
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_focal_loss",
                     inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
                     outputs={"Out": [out]},
                     attrs={"gamma": gamma, "alpha": alpha})
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_max_up_bound": soft_max_up_bound,
                            "soft_max_lower_bound": soft_max_lower_bound})
    return out


def dice_loss(input, label, epsilon=1e-5):
    """Composed like the reference layer (reference: layers/nn.py
    dice_loss): 1 - 2*|X∩Y| / (|X|+|Y|)."""
    from .nn import reduce_sum, reduce_mean, one_hot
    label_oh = one_hot(label, input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label_oh, dim=reduce_dims)
    dice_denominator = reduce_sum(input, dim=reduce_dims) + reduce_sum(
        label_oh, dim=reduce_dims)
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    return reduce_mean(dice_score)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Composed (reference: layers/nn.py npair_loss)."""
    from .nn import reduce_mean, reduce_sum, softmax_with_cross_entropy, transpose, matmul
    from .tensor import cast
    reg_anchor = reduce_mean(reduce_sum(anchor * anchor, dim=1))
    reg_pos = reduce_mean(reduce_sum(positive * positive, dim=1))
    l2loss = (reg_anchor + reg_pos) * 0.25 * l2_reg
    labels = cast(labels, "float32")
    from .nn import reshape
    labels = reshape(labels, [labels.shape[0], 1])
    eq = cast(equal_all_pairs(labels), "float32")
    similarity = matmul(anchor, positive, transpose_y=True)
    denom = reduce_sum(eq, dim=1, keep_dim=True)
    target = eq / denom
    ce = softmax_with_cross_entropy(similarity, target, soft_label=True)
    return reduce_mean(ce) + l2loss


def equal_all_pairs(labels):
    """labels (B,1) -> (B,B) equality matrix, via broadcasting ops."""
    helper = LayerHelper("equal_all_pairs")
    from .nn import transpose
    lt = transpose(labels, [1, 0])
    out = helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("equal", inputs={"X": [labels], "Y": [lt]},
                     outputs={"Out": [out]})
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference(VarType.FP32)
    seq_num = helper.create_variable_for_type_inference(VarType.INT64)
    inputs = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        inputs["HypsLength"] = [input_length]
    if label_length is not None:
        inputs["RefsLength"] = [label_length]
    helper.append_op("edit_distance", inputs=inputs,
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized})
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    helper = LayerHelper("chunk_eval")
    precision = helper.create_variable_for_type_inference(VarType.FP32)
    recall = helper.create_variable_for_type_inference(VarType.FP32)
    f1 = helper.create_variable_for_type_inference(VarType.FP32)
    n_infer = helper.create_variable_for_type_inference(VarType.INT64)
    n_label = helper.create_variable_for_type_inference(VarType.INT64)
    n_correct = helper.create_variable_for_type_inference(VarType.INT64)
    inputs = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length]
    helper.append_op("chunk_eval", inputs=inputs,
                     outputs={"Precision": [precision], "Recall": [recall],
                              "F1-Score": [f1], "NumInferChunks": [n_infer],
                              "NumLabelChunks": [n_label],
                              "NumCorrectChunks": [n_correct]},
                     attrs={"num_chunk_types": num_chunk_types,
                            "chunk_scheme": chunk_scheme,
                            "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, n_infer, n_label, n_correct


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None, seed=0):
    helper = LayerHelper("sampled_softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("sampled_softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Loss": [loss]},
                     attrs={"num_samples": num_samples, "seed": seed})
    return loss


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op("rank_loss",
                     inputs={"Label": [label], "Left": [left], "Right": [right]},
                     outputs={"Out": [out]})
    return out


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  cell_clip=None, proj_clip=None, length=None):
    """reference: layers/nn.py dynamic_lstmp (lstmp_op.cc).  Input is the
    (B, T, 4H) x-projection like dynamic_lstm; returns (projection, cell)."""
    helper = LayerHelper("dynamic_lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    w = helper.create_parameter(param_attr, shape=[proj_size, 4 * hidden],
                                dtype=dtype)
    wproj = helper.create_parameter(param_attr, shape=[hidden, proj_size],
                                    dtype=dtype)
    # 7H bias when peepholes are on: 4H gate bias + W_ic/W_fc/W_oc
    # diagonals (reference: lstmp_op.cc bias layout)
    bias_width = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(bias_attr, shape=[1, bias_width],
                                   dtype=dtype, is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    lh = helper.create_variable_for_type_inference(dtype)
    lc = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [w], "ProjWeight": [wproj],
              "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if length is not None:
        inputs["SequenceLength"] = [length]
    helper.append_op("dynamic_lstmp", inputs=inputs,
                     outputs={"Projection": [proj], "Cell": [cell],
                              "LastH": [lh], "LastC": [lc]},
                     attrs={"is_reverse": is_reverse,
                            "use_peepholes": use_peepholes,
                            "cell_clip": cell_clip or 0.0,
                            "proj_clip": proj_clip or 0.0,
                            "proj_activation": proj_activation})
    return proj, cell


def gather_tree(ids, parents):
    helper = LayerHelper("gather_tree")
    out = helper.create_variable_for_type_inference(ids.dtype)
    helper.append_op("gather_tree", inputs={"Ids": [ids], "Parents": [parents]},
                     outputs={"Out": [out]})
    return out


# --------------------------------------------------------------------------
# debug / infra layers
# --------------------------------------------------------------------------
def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: layers/control_flow.py Print — forwards input and prints
    host-side via the print op."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("print", inputs={"In": [input]}, outputs={"Out": [out]},
                     attrs={"first_n": first_n, "message": message or "",
                            "summarize": summarize,
                            "print_tensor_name": print_tensor_name,
                            "print_tensor_type": print_tensor_type,
                            "print_tensor_shape": print_tensor_shape,
                            "print_phase": print_phase.upper()})
    return out


def Assert(cond, data=None, summarize=20, name=None):
    """reference: layers/control_flow.py Assert — host-side check."""
    helper = LayerHelper("assert", name=name)
    helper.append_op("assert_op", inputs={"Cond": [cond],
                                          "Data": list(data or [])},
                     outputs={}, attrs={"summarize": summarize})
    return None


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference: layers/nn.py autoincreased_step_counter — a persistable
    int64 counter incremented once per run."""
    helper = LayerHelper("global_step_counter")
    counter = helper.create_global_variable(
        name=counter_name or "@STEP_COUNTER@", dtype=VarType.INT64, shape=[1],
        persistable=True)
    helper.startup_program.global_block().create_var(
        name=counter.name, dtype=VarType.INT64, shape=[1], persistable=True)
    sb = helper.startup_program.global_block()
    sb.append_op("fill_constant", inputs={},
                 outputs={"Out": [counter.name]},
                 attrs={"shape": [1], "value": float(begin - step),
                        "dtype": int(VarType.INT64)})
    helper.append_op("increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


# py_func moved to layers/nn.py (r5): ONE registered "py_func" op type
# lowering to jax.pure_callback — the program stays a single jitted XLA
# computation instead of splitting into hybrid segments per call, and
# the backward follows the reference (x, out, out@grad)-minus-skip
# contract (ops/py_func_op.py).


# --------------------------------------------------------------------------
# single-step RNN units (ops in ops/sequence_ops.py)
# --------------------------------------------------------------------------
def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference: layers/rnn original lstm_unit — fc([x, h]) then one
    lstm_unit op step; returns (hidden, cell)."""
    from .nn import fc, concat
    helper = LayerHelper("lstm_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    size = cell_t_prev.shape[-1]
    cat = concat([x_t, hidden_t_prev], axis=-1)
    gates = fc(cat, 4 * size, param_attr=param_attr, bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op("lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """reference: layers/nn.py gru_unit — one GRU step on the
    pre-computed input projection (size = 3*hidden)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    hidden_size = size // 3
    w = helper.create_parameter(param_attr, shape=[hidden_size, 3 * hidden_size],
                                dtype=dtype)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[1, 3 * hidden_size],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    gate = helper.create_variable_for_type_inference(dtype)
    reset_h = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gru_unit", inputs=inputs,
                     outputs={"Gate": [gate], "ResetHiddenPrev": [reset_h],
                              "Hidden": [updated]},
                     attrs={"origin_mode": origin_mode})
    return updated, reset_h, gate


# --------------------------------------------------------------------------
# CTR / instance-filter utilities
# --------------------------------------------------------------------------
def continuous_value_model(input, cvm, use_cvm=True):
    """reference: layers/nn.py continuous_value_model (cvm op)."""
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cvm", inputs={"X": [input], "CVM": [cvm]},
                     outputs={"Y": [out]}, attrs={"use_cvm": use_cvm})
    return out


def filter_by_instag(ins, ins_tag, filter_tag, is_lod, out_val_if_empty=0):
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype)
    loss_weight = helper.create_variable_for_type_inference(VarType.FP32)
    mmap = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("filter_by_instag",
                     inputs={"Ins": [ins], "Ins_tag": [ins_tag],
                             "Filter_tag": [filter_tag]},
                     outputs={"Out": [out], "LossWeight": [loss_weight],
                              "IndexMap": [mmap]},
                     attrs={"is_lod": is_lod,
                            "out_val_if_empty": out_val_if_empty})
    return out, loss_weight


# --------------------------------------------------------------------------
# reader / io conveniences (reference: layers/io.py)
# --------------------------------------------------------------------------
def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """reference: layers/io.py py_reader.  Returns a DataLoader-backed
    reader object with decorate_paddle_reader/decorate_tensor_provider
    plus data vars, matching the common usage pattern."""
    from ..reader import PyReader
    return PyReader(capacity=capacity, shapes=shapes, dtypes=dtypes,
                    use_double_buffer=use_double_buffer, name=name)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    from ..reader import PyReader
    return PyReader(capacity=capacity, feed_list=feed_list,
                    use_double_buffer=use_double_buffer, name=name)


def double_buffer(reader, place=None, name=None):
    """Double buffering is built into the DataLoader prefetch thread —
    identity here (reference: layers/io.py double_buffer)."""
    return reader


def read_file(reader):
    """reference: layers/io.py read_file — pop the next batch's vars."""
    return reader.read_file() if hasattr(reader, "read_file") else reader


def load(out, file_path, load_as_fp16=None):
    """reference: layers/io.py load — load one saved variable into out."""
    from .. import io as _io
    helper = LayerHelper("load")

    def _load_fn():
        import pickle
        with open(file_path, "rb") as f:
            return pickle.load(f)

    # host op: read at execution time, bind into the out var
    from ..ops.registry import op as register
    _PY_FUNC_COUNTER[0] += 1
    op_type = f"load_{_PY_FUNC_COUNTER[0]}"

    @register(op_type, no_grad=True, host=True)
    def _lower(ctx):
        import jax.numpy as jnp
        ctx.set_out("Out", jnp.asarray(_load_fn()))

    helper.append_op(op_type, inputs={}, outputs={"Out": [out]})
    return out


# --------------------------------------------------------------------------
# doc/codegen decorators (reference: layers/layer_function_generator.py)
# --------------------------------------------------------------------------
def deprecated(since=None, instead=None, reason=""):
    def deco(fn):
        import functools, warnings

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            warnings.warn(f"{fn.__name__} is deprecated"
                          + (f"; use {instead}" if instead else ""),
                          DeprecationWarning, stacklevel=2)
            return fn(*a, **kw)

        return wrapper

    return deco


def templatedoc(op_type=None):
    def deco(fn):
        return fn

    return deco


def autodoc(comment=""):
    def deco(fn):
        return fn

    return deco


def generate_layer_fn(op_type):
    """Build a generic LayerHelper wrapper for a registered op type
    (reference: layer_function_generator.py generate_layer_fn)."""

    def fn(*args, **kwargs):
        helper = LayerHelper(op_type, **kwargs)
        inputs = {}
        if args:
            inputs["X"] = [args[0]] if not isinstance(args[0], (list, tuple)) \
                else list(args[0])
            if len(args) > 1:
                inputs["Y"] = [args[1]]
        dtype = None
        for vs in inputs.values():
            for v in vs:
                if hasattr(v, "dtype"):
                    dtype = v.dtype
                    break
        out = kwargs.pop("out", None) or helper.create_variable_for_type_inference(
            dtype or VarType.FP32)
        attrs = {k: v for k, v in kwargs.items()
                 if k not in ("name", "param_attr", "bias_attr", "act")}
        helper.append_op(op_type, inputs=inputs, outputs={"Out": [out]},
                         attrs=attrs)
        return out

    fn.__name__ = op_type
    return fn


def generate_activation_fn(op_type):
    return _simple(op_type)


def einsum(equation, *operands):
    """reference: paddle 2.x paddle.einsum — general contraction op."""
    helper = LayerHelper("einsum")
    out = helper.create_variable_for_type_inference(operands[0].dtype)
    helper.append_op("einsum", inputs={"Operands": list(operands)},
                     outputs={"Out": [out]}, attrs={"equation": equation})
    return out


# public surface: every function defined in this module (keeps the
# star-import in layers/__init__.py from leaking np/LayerHelper/etc.)
__all__ = [
    _n for _n, _v in list(globals().items())
    if not _n.startswith("_") and callable(_v)
    and getattr(_v, "__module__", None) == __name__
]
