"""Monkey-patch Variable with python operators.

Reference: python/paddle/fluid/layers/math_op_patch.py:58 monkey_patch_variable.
"""
from __future__ import annotations

from ..framework.core import Variable
from ..framework.dtype import VarType, is_float
from ..layer_helper import LayerHelper


def _create_op(op_type, x, y=None, axis=-1, reverse=False):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype)
    if y is None:
        helper.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]})
    else:
        a, b = (y, x) if reverse else (x, y)
        helper.append_op(op_type, inputs={"X": [a], "Y": [b]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def _scalar_op(x, scale, bias):
    helper = LayerHelper("scale")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias)})
    return out


def _to_var(x, ref: Variable):
    """Promote python scalar to a filled-constant var broadcastable to ref."""
    from . import tensor as tensor_layers

    return tensor_layers.fill_constant([1], ref.dtype, float(x))


def _binary(op_type, reverse=False, scalar_fn=None):
    def impl(self, other):
        if isinstance(other, (int, float)):
            if scalar_fn is not None:
                return scalar_fn(self, other)
            other = _to_var(other, self)
        elif not isinstance(other, Variable):
            return NotImplemented
        return _create_op(op_type, self, other, reverse=reverse)

    return impl


_INT_MAX = 2 ** 31 - 1
_INT_MIN = -(2 ** 31)


def _getitem_impl(self, item):
    """reference: framework.py:1672 Variable.__getitem__ /
    _getitem_impl_ — int / slice / tuple indexing on a static Variable
    lowers to slice / strided_slice ops (ints drop their axis via
    decrease_axis, matching numpy); a scalar-tensor index lowers to
    gather; LoDTensorArray vars read elements (array_read)."""
    from ..framework.dtype import VarType

    if self.type == VarType.LOD_TENSOR_ARRAY:
        from . import tensor as tensor_layers
        from .control_flow import array_length, array_read

        i = item
        if isinstance(i, int):
            if i < 0:
                i = array_length(self) + i
            else:
                i = tensor_layers.fill_constant([1], "int64", i)
        elif not isinstance(i, Variable):
            raise TypeError(
                f"LoDTensorArray index must be int or Variable, got "
                f"{type(i).__name__}")
        return array_read(self, i)

    items = list(item) if isinstance(item, tuple) else [item]
    ndim = len(self.shape)
    if sum(1 for it in items if it is Ellipsis) > 1:
        # numpy semantics: a second Ellipsis is ambiguous, not a
        # zero-length expansion (x[..., ..., 0] must not mean x[0])
        raise IndexError(
            "an index can only have a single ellipsis ('...')")
    if any(it is Ellipsis for it in items):
        n_spec = sum(1 for it in items if it is not Ellipsis)
        expanded = []
        for it in items:
            if it is Ellipsis:
                expanded.extend([slice(None)] * (ndim - n_spec))
            else:
                expanded.append(it)
        items = expanded
    if len(items) > ndim:
        raise IndexError(
            f"too many indices ({len(items)}) for var of rank {ndim}")

    # a single tensor index on the leading axis: gather (numpy fancy-row
    # semantics); a SCALAR index additionally drops the axis
    if len(items) == 1 and isinstance(items[0], Variable):
        from . import nn as nn_layers

        idx = items[0]
        ishape = tuple(idx.shape or ())
        if ishape != ():
            if len(ishape) != 1:
                raise TypeError(
                    f"tensor index must be a scalar or 1-D vector, got "
                    f"shape {ishape}")
            # numpy fancy-row semantics: a 1-D index (even length-1)
            # keeps its axis — x[[0]] is (1, ...), not (...)
            return nn_layers.gather(self, nn_layers.cast(idx, "int64"))
        # 0-d scalar index drops the axis
        row = nn_layers.gather(self, nn_layers.reshape(
            nn_layers.cast(idx, "int64"), [1]))
        tail = [int(d) for d in self.shape[1:]]
        return nn_layers.reshape(row, tail) if tail else \
            nn_layers.reshape(row, [1])

    # two passes, both rank-preserving until the final decrease:
    # non-unit-step slices -> strided_slice; ints + unit slices ->
    # slice (ints drop their axis via decrease_axis)
    import operator

    def _bound(v, what):
        if v is None:
            return None
        try:
            return operator.index(v)  # int / np integer
        except TypeError:
            raise TypeError(
                f"slice {what} on a static Variable must be a python "
                f"int, got {type(v).__name__}; use layers.slice / "
                "layers.gather for tensor bounds") from None

    str_axes, str_starts, str_ends, str_strides = [], [], [], []
    axes, starts, ends, decrease = [], [], [], []
    for ax, it in enumerate(items):
        if not isinstance(it, (slice, Variable)):
            try:
                it = operator.index(it)  # np integer scalars index too
            except TypeError:
                pass
        if isinstance(it, int):
            axes.append(ax)
            starts.append(it)
            ends.append(it + 1 if it != -1 else _INT_MAX)
            decrease.append(ax)
        elif isinstance(it, slice):
            st = 1 if it.step is None else _bound(it.step, "step")
            if st == 0:
                raise ValueError(f"invalid slice step {it.step!r}")
            s, e = _bound(it.start, "start"), _bound(it.stop, "stop")
            if s is None and e is None and st == 1:
                continue
            if st == 1:
                axes.append(ax)
                starts.append(0 if s is None else s)
                ends.append(_INT_MAX if e is None else e)
            else:
                str_axes.append(ax)
                str_starts.append((0 if st > 0 else _INT_MAX)
                                  if s is None else s)
                str_ends.append((_INT_MAX if st > 0 else _INT_MIN)
                                if e is None else e)
                str_strides.append(st)
        elif isinstance(it, Variable):
            raise TypeError(
                "tensor indices are only supported as a single leading "
                "index (x[i]); combine with layers.gather/gather_nd for "
                "more")
        else:
            raise TypeError(
                f"unsupported index {it!r} for a static Variable")

    out = self
    if str_axes:
        helper = LayerHelper("getitem")
        sliced = helper.create_variable_for_type_inference(self.dtype)
        helper.append_op(
            "strided_slice", inputs={"Input": [out]},
            outputs={"Out": [sliced]},
            attrs={"axes": str_axes, "starts": str_starts,
                   "ends": str_ends, "strides": str_strides})
        out = sliced
    if axes:
        helper = LayerHelper("getitem")
        sliced = helper.create_variable_for_type_inference(self.dtype)
        helper.append_op(
            "slice", inputs={"Input": [out]},
            outputs={"Out": [sliced]},
            attrs={"axes": axes, "starts": starts, "ends": ends,
                   "decrease_axis": decrease})
        out = sliced
    return out


def _not_iterable(self):
    # __getitem__ would otherwise enable the legacy iteration protocol,
    # and the clamping slice op never raises IndexError -> infinite loop
    raise TypeError(
        "static Variable is not iterable; index it (x[i]), or iterate "
        "inside dygraph_to_static / layers.while_loop")


def monkey_patch_variable():
    Variable.__getitem__ = _getitem_impl
    Variable.__iter__ = _not_iterable
    Variable.__add__ = _binary("elementwise_add",
                               scalar_fn=lambda x, s: _scalar_op(x, 1.0, s))
    Variable.__radd__ = Variable.__add__
    Variable.__sub__ = _binary("elementwise_sub",
                               scalar_fn=lambda x, s: _scalar_op(x, 1.0, -s))
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True,
                                scalar_fn=lambda x, s: _scalar_op(x, -1.0, s))
    Variable.__mul__ = _binary("elementwise_mul",
                               scalar_fn=lambda x, s: _scalar_op(x, s, 0.0))
    Variable.__rmul__ = Variable.__mul__
    Variable.__truediv__ = _binary(
        "elementwise_div", scalar_fn=lambda x, s: _scalar_op(x, 1.0 / s, 0.0)
    )
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__rpow__ = _binary("elementwise_pow", reverse=True)
    Variable.__mod__ = _binary("elementwise_mod")
    Variable.__floordiv__ = _binary("elementwise_floordiv")
    Variable.__neg__ = lambda self: _scalar_op(self, -1.0, 0.0)

    for name, op_type in [
        ("__eq__", "equal"), ("__ne__", "not_equal"), ("__lt__", "less_than"),
        ("__le__", "less_equal"), ("__gt__", "greater_than"),
        ("__ge__", "greater_equal"),
    ]:
        def cmp_impl(self, other, _op=op_type):
            if not isinstance(other, Variable):
                if isinstance(other, (int, float)):
                    other = _to_var(other, self)
                else:
                    return NotImplemented
            return _create_op(_op, self, other)

        setattr(Variable, name, cmp_impl)
    Variable.__hash__ = lambda self: id(self)
