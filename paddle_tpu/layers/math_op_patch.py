"""Monkey-patch Variable with python operators.

Reference: python/paddle/fluid/layers/math_op_patch.py:58 monkey_patch_variable.
"""
from __future__ import annotations

from ..framework.core import Variable
from ..framework.dtype import VarType, is_float
from ..layer_helper import LayerHelper


def _create_op(op_type, x, y=None, axis=-1, reverse=False):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype)
    if y is None:
        helper.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]})
    else:
        a, b = (y, x) if reverse else (x, y)
        helper.append_op(op_type, inputs={"X": [a], "Y": [b]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def _scalar_op(x, scale, bias):
    helper = LayerHelper("scale")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias)})
    return out


def _to_var(x, ref: Variable):
    """Promote python scalar to a filled-constant var broadcastable to ref."""
    from . import tensor as tensor_layers

    return tensor_layers.fill_constant([1], ref.dtype, float(x))


def _binary(op_type, reverse=False, scalar_fn=None):
    def impl(self, other):
        if isinstance(other, (int, float)):
            if scalar_fn is not None:
                return scalar_fn(self, other)
            other = _to_var(other, self)
        elif not isinstance(other, Variable):
            return NotImplemented
        return _create_op(op_type, self, other, reverse=reverse)

    return impl


def monkey_patch_variable():
    Variable.__add__ = _binary("elementwise_add",
                               scalar_fn=lambda x, s: _scalar_op(x, 1.0, s))
    Variable.__radd__ = Variable.__add__
    Variable.__sub__ = _binary("elementwise_sub",
                               scalar_fn=lambda x, s: _scalar_op(x, 1.0, -s))
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True,
                                scalar_fn=lambda x, s: _scalar_op(x, -1.0, s))
    Variable.__mul__ = _binary("elementwise_mul",
                               scalar_fn=lambda x, s: _scalar_op(x, s, 0.0))
    Variable.__rmul__ = Variable.__mul__
    Variable.__truediv__ = _binary(
        "elementwise_div", scalar_fn=lambda x, s: _scalar_op(x, 1.0 / s, 0.0)
    )
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__rpow__ = _binary("elementwise_pow", reverse=True)
    Variable.__mod__ = _binary("elementwise_mod")
    Variable.__floordiv__ = _binary("elementwise_floordiv")
    Variable.__neg__ = lambda self: _scalar_op(self, -1.0, 0.0)

    for name, op_type in [
        ("__eq__", "equal"), ("__ne__", "not_equal"), ("__lt__", "less_than"),
        ("__le__", "less_equal"), ("__gt__", "greater_than"),
        ("__ge__", "greater_equal"),
    ]:
        def cmp_impl(self, other, _op=op_type):
            if not isinstance(other, Variable):
                if isinstance(other, (int, float)):
                    other = _to_var(other, self)
                else:
                    return NotImplemented
            return _create_op(_op, self, other)

        setattr(Variable, name, cmp_impl)
    Variable.__hash__ = lambda self: id(self)
