"""fluid.layers RNN API: cells, rnn()/birnn(), fused lstm/gru, StaticRNN,
beam search.

Reference: python/paddle/fluid/layers/rnn.py (RNNCell/LSTMCell/GRUCell,
rnn, birnn, beam search helpers), layers/nn.py dynamic_lstm/dynamic_gru,
layers/control_flow.py StaticRNN.

TPU-first: generic cells unroll over the (static) padded time axis at
graph-build time — XLA re-rolls/fuses the unrolled steps; the fused
``lstm``/``gru`` ops lower to ``lax.scan`` (one compiled while loop whose
body is MXU matmuls), which is the path to use for speed.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Variable, in_dygraph_mode
from ..layer_helper import LayerHelper
from . import nn as _nn
from . import tensor as _tensor


class RNNCell:
    """reference: layers/rnn.py RNNCell — step interface."""

    def call(self, inputs, states):
        raise NotImplementedError

    def __call__(self, inputs, states):
        return self.call(inputs, states)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0):
        raise NotImplementedError


class LSTMCell(RNNCell):
    """reference: layers/rnn.py LSTMCell — one step of a basic LSTM built
    from fc ops, so it is usable inside rnn()/StaticRNN unrolling."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, forget_bias=1.0,
                 dtype="float32", name="LSTMCell"):
        self.hidden_size = hidden_size
        self.param_attr = param_attr
        self.bias_attr = bias_attr
        self.forget_bias = forget_bias
        self.dtype = dtype
        self.name = name

    def call(self, inputs, states):
        pre_hidden, pre_cell = states
        concat = _tensor.concat([inputs, pre_hidden], axis=1)
        gates = _nn.fc(concat, 4 * self.hidden_size,
                       param_attr=self.param_attr, bias_attr=self.bias_attr)
        helper = LayerHelper("lstm_unit", input=gates)
        c = helper.create_variable_for_type_inference(self.dtype)
        h = helper.create_variable_for_type_inference(self.dtype)
        helper.append_op("lstm_unit",
                         inputs={"X": [gates], "C_prev": [pre_cell]},
                         outputs={"C": [c], "H": [h]},
                         attrs={"forget_bias": self.forget_bias})
        return h, [h, c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


class GRUCell(RNNCell):
    """reference: layers/rnn.py GRUCell."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype="float32",
                 name="GRUCell"):
        self.hidden_size = hidden_size
        self.param_attr = param_attr
        self.bias_attr = bias_attr
        self.dtype = dtype
        self.name = name
        self._helper = LayerHelper(name)
        self._weight = None

    def call(self, inputs, states):
        pre_hidden = states[0] if isinstance(states, (list, tuple)) else states
        xproj = _nn.fc(inputs, 3 * self.hidden_size,
                       param_attr=self.param_attr, bias_attr=self.bias_attr)
        helper = LayerHelper("gru_unit", input=xproj)
        if self._weight is None:
            # when the cell's params carry an explicit name, derive a
            # stable name for the hidden weight too so a separately
            # built program (e.g. a decode graph) shares it by scope;
            # all other ParamAttr fields (initializer, trainable, ...)
            # carry over so both weights get the same treatment
            from ..param_attr import ParamAttr

            attr = ParamAttr._to_attr(self.param_attr)
            w_attr = None
            if attr is not None and attr.name:
                import copy

                w_attr = copy.copy(attr)
                w_attr.name = attr.name + "_hidden_w"
            self._weight = helper.create_parameter(
                w_attr, shape=[self.hidden_size, 3 * self.hidden_size],
                dtype=self.dtype)
        gate = helper.create_variable_for_type_inference(self.dtype)
        rhp = helper.create_variable_for_type_inference(self.dtype)
        hidden = helper.create_variable_for_type_inference(self.dtype)
        helper.append_op("gru_unit",
                         inputs={"Input": [xproj], "HiddenPrev": [pre_hidden],
                                 "Weight": [self._weight]},
                         outputs={"Gate": [gate], "ResetHiddenPrev": [rhp],
                                  "Hidden": [hidden]})
        return hidden, [hidden]

    @property
    def state_shape(self):
        return [[self.hidden_size]]


def _zeros_like_state(batch_ref, size, dtype):
    """[N, size] zeros matching batch_ref's leading dim."""
    return _tensor.fill_constant_batch_size_like(
        batch_ref, shape=[-1, size], dtype=dtype, value=0.0)


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """reference: layers/rnn.py rnn — run a cell over the time axis.

    Build-time unroll over the static T; per-step masking replicates the
    reference's sequence_length semantics (state freezes past the end).
    """
    T_axis = 0 if time_major else 1
    T = inputs.shape[T_axis]
    if T < 0:
        raise ValueError("rnn() needs a static time dimension on TPU")
    hidden = getattr(cell, "hidden_size", None)
    if initial_states is None:
        shapes = cell.state_shape
        initial_states = [
            _zeros_like_state(inputs, s[-1], "float32") for s in shapes
        ]
    states = list(initial_states) if isinstance(initial_states, (list, tuple)) \
        else [initial_states]
    if sequence_length is not None:
        from .sequence_lod import sequence_mask
        mask_all = sequence_mask(sequence_length, maxlen=T, dtype="float32")
    step_outs = []
    order = range(T - 1, -1, -1) if is_reverse else range(T)
    for t in order:
        xt = _nn.squeeze(
            _nn.slice(inputs, axes=[T_axis], starts=[t], ends=[t + 1]),
            axes=[T_axis])
        out, new_states = cell(xt, states if len(states) > 1 else states[0])
        if not isinstance(new_states, (list, tuple)):
            new_states = [new_states]
        new_states = list(new_states)
        if sequence_length is not None:
            mt = _nn.slice(mask_all, axes=[1], starts=[t], ends=[t + 1])
            new_states = [
                _nn.elementwise_add(
                    _nn.elementwise_mul(ns, mt),
                    _nn.elementwise_mul(s, _nn.scale(mt, -1.0, 1.0)))
                for ns, s in zip(new_states, states)
            ]
            out = _nn.elementwise_mul(out, mt)
        states = new_states
        step_outs.append(out)
    if is_reverse:
        step_outs.reverse()
    outs = _nn.stack(step_outs, axis=T_axis)
    final = states if len(states) > 1 else states[0]
    return outs, final


def birnn(cell_fw, cell_bw, inputs, initial_states=None, sequence_length=None,
          time_major=False, **kwargs):
    """reference: layers/rnn.py birnn."""
    states_fw, states_bw = (initial_states if initial_states is not None
                            else (None, None))
    out_fw, st_fw = rnn(cell_fw, inputs, states_fw, sequence_length,
                        time_major=time_major)
    out_bw, st_bw = rnn(cell_bw, inputs, states_bw, sequence_length,
                        time_major=time_major, is_reverse=True)
    out = _tensor.concat([out_fw, out_bw], axis=-1)
    return out, (st_fw, st_bw)


def lstm(input, init_h=None, init_c=None, max_len=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False, is_test=False,
         name=None, default_initializer=None, seed=-1, sequence_length=None):
    """reference: layers/nn.py lstm (cudnn LSTM) — fused scan-based op."""
    helper = LayerHelper("lstm", input=input, name=name)
    dtype = input.dtype
    D = input.shape[-1]
    dirs = 2 if is_bidirec else 1
    wis, whs, bs = [], [], []
    for l in range(num_layers):
        in_dim = D if l == 0 else hidden_size * dirs
        for d in range(dirs):
            wis.append(helper.create_parameter(
                None, shape=[in_dim, 4 * hidden_size], dtype=dtype))
            whs.append(helper.create_parameter(
                None, shape=[hidden_size, 4 * hidden_size], dtype=dtype))
            bs.append(helper.create_parameter(
                None, shape=[4 * hidden_size], dtype=dtype, is_bias=True))
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "WeightIh": wis, "WeightHh": whs, "Bias": bs}
    if init_h is not None:
        ins["InitH"] = [init_h]
    if init_c is not None:
        ins["InitC"] = [init_c]
    if sequence_length is not None:
        ins["SequenceLength"] = [sequence_length]
    helper.append_op("lstm", inputs=ins,
                     outputs={"Out": [out], "LastH": [last_h], "LastC": [last_c]},
                     attrs={"is_bidirec": is_bidirec, "num_layers": num_layers,
                            "hidden_size": hidden_size,
                            "dropout_prob": dropout_prob})
    return out, last_h, last_c


def gru(input, hidden_size, num_layers=1, is_bidirec=False, init_h=None,
        name=None, sequence_length=None):
    """Fused multi-layer GRU (scan-based; the reference reaches this
    capability by stacking dynamic_gru — gru_op.cc)."""
    helper = LayerHelper("gru", input=input, name=name)
    dtype = input.dtype
    D = input.shape[-1]
    dirs = 2 if is_bidirec else 1
    wis, whs, bs = [], [], []
    for l in range(num_layers):
        in_dim = D if l == 0 else hidden_size * dirs
        for d in range(dirs):
            wis.append(helper.create_parameter(
                None, shape=[in_dim, 3 * hidden_size], dtype=dtype))
            whs.append(helper.create_parameter(
                None, shape=[hidden_size, 3 * hidden_size], dtype=dtype))
            bs.append(helper.create_parameter(
                None, shape=[3 * hidden_size], dtype=dtype, is_bias=True))
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "WeightIh": wis, "WeightHh": whs, "Bias": bs}
    if init_h is not None:
        ins["InitH"] = [init_h]
    if sequence_length is not None:
        ins["SequenceLength"] = [sequence_length]
    helper.append_op("gru", inputs=ins,
                     outputs={"Out": [out], "LastH": [last_h]},
                     attrs={"is_bidirec": is_bidirec, "num_layers": num_layers,
                            "hidden_size": hidden_size})
    return out, last_h


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 sequence_length=None):
    """reference: layers/nn.py dynamic_lstm — input is the [N, T, 4H]
    x-projection (size = 4H)."""
    hidden = size // 4
    helper = LayerHelper("dynamic_lstm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    w = helper.create_parameter(param_attr, shape=[hidden, 4 * hidden], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 4 * hidden], dtype=dtype,
                                is_bias=True)
    hid = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    lh = helper.create_variable_for_type_inference(dtype)
    lc = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    if sequence_length is not None:
        ins["SequenceLength"] = [sequence_length]
    helper.append_op("dynamic_lstm", inputs=ins,
                     outputs={"Hidden": [hid], "Cell": [cell],
                              "LastH": [lh], "LastC": [lc]},
                     attrs={"is_reverse": is_reverse,
                            "use_peepholes": use_peepholes})
    return hid, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32",
                sequence_length=None):
    """reference: layers/nn.py dynamic_gru — input is the [N, T, 3H]
    x-projection (size = H)."""
    helper = LayerHelper("dynamic_gru", input=input, param_attr=param_attr,
                         bias_attr=bias_attr)
    w = helper.create_parameter(param_attr, shape=[size, 3 * size], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 3 * size], dtype=dtype,
                                is_bias=True)
    hid = helper.create_variable_for_type_inference(dtype)
    lh = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if sequence_length is not None:
        ins["SequenceLength"] = [sequence_length]
    helper.append_op("dynamic_gru", inputs=ins,
                     outputs={"Hidden": [hid], "LastH": [lh]},
                     attrs={"is_reverse": is_reverse})
    return hid


class StaticRNN:
    """reference: layers/control_flow.py StaticRNN — step-program builder
    unrolled over the (static) time axis.

    The reference records the step body into a sub-block executed by
    recurrent_op; here the body's ops are recorded in the main block for
    t=0 and then **replayed with renamed vars for t=1..T-1** (XLA re-rolls
    and fuses the unrolled steps).  Inputs are batch-major padded
    [N, T, ...]."""

    def __init__(self, name=None):
        from ..framework.core import default_main_program
        self._block = default_main_program().current_block()
        self._start_idx = None
        self._step_input_ops = {}   # op id -> input Variable ([N,T,...])
        self._memories = {}         # init var name -> update var name
        self._init_op_ids = set()   # memory-init ops: run once, not per-step
        self._outputs = []
        self._T = None

    def step(self):
        rnn_self = self

        class _Guard:
            def __enter__(self):
                rnn_self._start_idx = len(rnn_self._block.ops)
                return rnn_self

            def __exit__(self, exc_type, *a):
                if exc_type is None:
                    rnn_self._unroll()
                return False

        return _Guard()

    def step_input(self, x):
        if self._T is None:
            self._T = x.shape[1]
        elif x.shape[1] != self._T:
            raise ValueError("StaticRNN step inputs disagree on T")
        sliced = _nn.slice(x, axes=[1], starts=[0], ends=[1])
        sq = _nn.squeeze(sliced, axes=[1])
        # the two ops just appended are the per-step extraction; remember
        # them so the replay can re-target the slice at t
        self._step_input_ops[id(self._block.ops[-2])] = x
        return sq

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               dtype="float32"):
        if init is None:
            before = len(self._block.ops)
            init = _tensor.fill_constant_batch_size_like(
                batch_ref, shape=[-1] + list(shape), dtype=dtype,
                value=init_value)
            for o in self._block.ops[before:]:
                self._init_op_ids.add(id(o))
        self._memories[init.name] = None
        return init

    def update_memory(self, mem, new_val):
        if mem.name not in self._memories:
            raise ValueError(f"{mem.name} is not a StaticRNN memory")
        self._memories[mem.name] = new_val.name

    def step_output(self, out):
        self._outputs.append(out)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _unroll(self):
        block = self._block
        T = self._T
        if T is None or T < 0:
            raise ValueError("StaticRNN needs a static time dimension")
        recorded = [o for o in block.ops[self._start_idx:]
                    if id(o) not in self._init_op_ids]
        out_names_t = {v.name: [v.name] for v in self._outputs}
        prev_step_name = {init: (upd or init)
                          for init, upd in self._memories.items()}
        for t in range(1, T):
            rename = {}
            # memory reads resolve to last step's update vars
            for init, upd in self._memories.items():
                rename[init] = prev_step_name[init]
            new_update = {}
            for rec in recorded:
                attrs = dict(rec.attrs)
                if id(rec) in self._step_input_ops:
                    attrs["starts"] = [t]
                    attrs["ends"] = [t + 1]
                ins = {s: [rename.get(n, n) for n in ns]
                       for s, ns in rec.inputs.items()}
                outs = {}
                for s, ns in rec.outputs.items():
                    new_ns = []
                    for n in ns:
                        src = block._find_var_recursive(n)
                        nn_name = f"{n}@rnn_t{t}"
                        if src is not None:
                            block.create_var(name=nn_name, shape=src.shape,
                                             dtype=src.dtype,
                                             stop_gradient=src.stop_gradient)
                        rename[n] = nn_name
                        new_ns.append(nn_name)
                        for init, upd in self._memories.items():
                            if upd == n:
                                new_update[init] = nn_name
                    outs[s] = new_ns
                block.append_op(rec.type, inputs=ins, outputs=outs, attrs=attrs)
            for init in self._memories:
                prev_step_name[init] = new_update.get(
                    init, prev_step_name[init])
            for name in out_names_t:
                out_names_t[name].append(rename.get(name, name))
        # stack per-step outputs into [N, T, ...]
        self._stacked = []
        for v in self._outputs:
            steps = [block.var(n) if n != v.name else v
                     for n in out_names_t[v.name]]
            self._stacked.append(_nn.stack(steps, axis=1))

    def __call__(self):
        if len(self._stacked) == 1:
            return self._stacked[0]
        return self._stacked


# --------------------------------------------------------------------------
# beam search wrappers
# --------------------------------------------------------------------------
def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None, return_parent_idx=True):
    """reference: layers/rnn.py beam_search (beam_search_op.cc)."""
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    sel_scores = helper.create_variable_for_type_inference(scores.dtype,
                                                           stop_gradient=True)
    parent = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op("beam_search",
                     inputs={"PreIds": [pre_ids], "PreScores": [pre_scores],
                             "Scores": [scores]},
                     outputs={"SelectedIds": [sel_ids],
                              "SelectedScores": [sel_scores],
                              "ParentIdx": [parent]},
                     attrs={"beam_size": beam_size, "end_id": end_id})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, parent_idx, beam_size, end_id, name=None):
    """reference: layers/rnn.py beam_search_decode.  ``ids``/``scores``/
    ``parent_idx`` are lists of per-step vars."""
    helper = LayerHelper("beam_search_decode", name=name)
    out_ids = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    out_scores = helper.create_variable_for_type_inference("float32",
                                                           stop_gradient=True)
    out_len = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op("beam_search_decode",
                     inputs={"Ids": list(ids), "Scores": list(scores),
                             "ParentIdx": list(parent_idx)},
                     outputs={"SentenceIds": [out_ids],
                              "SentenceScores": [out_scores],
                              "SentenceLength": [out_len]},
                     attrs={"beam_size": beam_size, "end_id": end_id})
    # the reference conveys hypothesis lengths via LoD; on the padded
    # representation the explicit length vector is the only carrier
    return out_ids, out_scores, out_len
