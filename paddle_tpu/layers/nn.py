"""fluid.layers NN graph-builder functions.

Reference: python/paddle/fluid/layers/nn.py (180 public fns; the most-used
subset is implemented here and the corpus grows with the build).  Each
function builds vars + ops via LayerHelper; the ops lower to jax in
ops/*.py.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Variable, in_dygraph_mode
from ..framework.dtype import VarType, convert_dtype
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer, NormalInitializer


def _single(x, n=2):
    return [x] * n if isinstance(x, int) else list(x)


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    """reference: layers/nn.py fc — mul(+sum) + bias + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    dtype = inputs[0].dtype
    mul_results = []
    for inp in inputs:
        in_shape = inp.shape
        flat = int(np.prod([s if s >= 0 else -s for s in in_shape[num_flatten_dims:]]))
        w = helper.create_parameter(
            param_attr, shape=[flat, size], dtype=dtype
        )
        out = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            "mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [out]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(out)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op("sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims, bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """reference: layers/nn.py embedding (lookup_table op)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(param_attr, shape=size, dtype=dtype)
    if is_distributed:
        w.is_distributed = True
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1 if padding_idx is None
        else padding_idx if padding_idx >= 0
        else size[0] + padding_idx
    )
    helper.append_op(
        "lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={
            "padding_idx": padding_idx,
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
        },
    )
    return out


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
    data_format="NCHW",
):
    """reference: layers/nn.py conv2d."""
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    channel_axis = 1 if data_format == "NCHW" else 3
    num_channels = input.shape[channel_axis]
    fsize = _single(filter_size)
    stride = _single(stride)
    dilation = _single(dilation)
    padding_algorithm = "EXPLICIT"
    if isinstance(padding, str):
        padding_algorithm = padding.upper()
        padding = [0, 0]
    else:
        padding = _single(padding)

    op_type = (
        "depthwise_conv2d"
        if groups == num_channels and num_filters % num_channels == 0 and groups != 1
        else "conv2d"
    )
    filter_shape = [num_filters, num_channels // groups] + fsize
    fan_in = (num_channels // groups) * fsize[0] * fsize[1]
    default_init = NormalInitializer(0.0, (2.0 / fan_in) ** 0.5)
    w = helper.create_parameter(
        param_attr, shape=filter_shape, dtype=dtype, default_initializer=default_init
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        op_type,
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
            "padding_algorithm": padding_algorithm,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=channel_axis,
                                    dim_end=channel_axis + 1, bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    stride = _single(stride)
    dilation = _single(dilation)
    padding = _single(padding)
    if filter_size is None:
        raise ValueError("filter_size required (output_size-only not supported yet)")
    fsize = _single(filter_size)
    w = helper.create_parameter(
        param_attr, shape=[num_channels, num_filters // groups] + fsize, dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2, bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    exclusive=True,
    data_format="NCHW",
    name=None,
):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    padding_algorithm = "EXPLICIT"
    if isinstance(pool_padding, str):
        padding_algorithm = pool_padding.upper()
        pool_padding = [0, 0]
    helper.append_op(
        "pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _single(pool_size),
            "strides": _single(pool_stride),
            "paddings": _single(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
            "padding_algorithm": padding_algorithm,
        },
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _single(pool_size),
            "adaptive": True,
            "strides": [1, 1],
            "paddings": [0, 0],
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=True,
    use_global_stats=False,
):
    """reference: layers/nn.py batch_norm."""
    from ..param_attr import ParamAttr

    helper = LayerHelper("batch_norm", name=name, act=act)
    dtype = input.dtype
    channel_axis = 1 if data_layout == "NCHW" else len(input.shape) - 1
    c = input.shape[channel_axis]
    scale = helper.create_parameter(
        ParamAttr._to_attr(param_attr), shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), shape=[c], dtype=dtype, is_bias=True
    )
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False),
        shape=[c], dtype=dtype, default_initializer=ConstantInitializer(0.0),
    )
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False),
        shape=[c], dtype=dtype, default_initializer=ConstantInitializer(1.0),
    )
    mean.stop_gradient = True
    variance.stop_gradient = True

    y = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "batch_norm",
        inputs={
            "X": [input], "Scale": [scale], "Bias": [bias],
            "Mean": [mean], "Variance": [variance],
        },
        outputs={
            "Y": [y], "MeanOut": [mean], "VarianceOut": [variance],
            "SavedMean": [saved_mean], "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum, "epsilon": epsilon, "is_test": is_test,
            "data_layout": data_layout, "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(y, act)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("layer_norm", name=name, act=act)
    dtype = input.dtype
    import math

    norm_shape = [int(np.prod([s for s in input.shape[begin_norm_axis:]]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            param_attr, shape=norm_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape, dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "layer_norm",
        inputs=inputs,
        outputs={"Y": [y], "Mean": [mean], "Variance": [var]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(y, act)


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "fix_seed": seed is not None,
            "seed": seed or 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("softmax", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_softmax", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def _simple_unary(op_type):
    def fn(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]})
        return out

    fn.__name__ = op_type
    return fn


relu = _simple_unary("relu")
sigmoid = _simple_unary("sigmoid")
tanh = _simple_unary("tanh")
sqrt = _simple_unary("sqrt")
abs = _simple_unary("abs")
exp = _simple_unary("exp")
log = _simple_unary("log")
square = _simple_unary("square")
ceil = _simple_unary("ceil")
floor = _simple_unary("floor")
round = _simple_unary("round")
sin = _simple_unary("sin")
cos = _simple_unary("cos")
softplus = _simple_unary("softplus")
softsign = _simple_unary("softsign")
rsqrt = _simple_unary("rsqrt")
reciprocal = _simple_unary("reciprocal")
logsigmoid = _simple_unary("logsigmoid")
erf = _simple_unary("erf")
sign = _simple_unary("sign")


def relu6(x, threshold=6.0, name=None):
    helper = LayerHelper("relu6", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("relu6", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"threshold": threshold})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("leaky_relu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"alpha": alpha})
    return out


def gelu(x, approximate=False):
    helper = LayerHelper("gelu")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("gelu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"approximate": approximate})
    return out


def swish(x, beta=1.0, name=None):
    helper = LayerHelper("swish", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("swish", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"beta": beta})
    return out


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    helper = LayerHelper("hard_swish", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("hard_swish", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"threshold": threshold, "scale": scale, "offset": offset})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    else:
        alpha_shape = [1] + list(x.shape[1:])
    alpha = helper.create_parameter(
        param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "mul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "matmul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
               "alpha": float(alpha)},
    )
    return out


def _elementwise(op_type):
    def fn(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        return helper.append_activation(out, act)

    fn.__name__ = op_type
    return fn


elementwise_add = _elementwise("elementwise_add")
elementwise_sub = _elementwise("elementwise_sub")
elementwise_mul = _elementwise("elementwise_mul")
elementwise_div = _elementwise("elementwise_div")
elementwise_max = _elementwise("elementwise_max")
elementwise_min = _elementwise("elementwise_min")
elementwise_pow = _elementwise("elementwise_pow")
elementwise_mod = _elementwise("elementwise_mod")
elementwise_floordiv = _elementwise("elementwise_floordiv")


def _reduce_layer(op_type):
    def fn(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if dim is None:
            attrs = {"reduce_all": True, "dim": [0], "keep_dim": keep_dim}
        else:
            dims = dim if isinstance(dim, (list, tuple)) else [dim]
            attrs = {"reduce_all": False, "dim": list(dims), "keep_dim": keep_dim}
        helper.append_op(op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs)
        return out

    fn.__name__ = op_type
    return fn


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")
reduce_all = _reduce_layer("reduce_all")
reduce_any = _reduce_layer("reduce_any")


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip_by_norm", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"max_norm": float(max_norm)})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    sq = square(x)
    s = reduce_sum(sq, dim=axis, keep_dim=True)
    helper = LayerHelper("l2_normalize", name=name)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sqrt", inputs={"X": [s]}, outputs={"Out": [norm]})
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("elementwise_div", inputs={"X": [x], "Y": [norm]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


# -- losses ----------------------------------------------------------------
def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "cross_entropy", inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
    axis=-1,
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index,
               "numeric_stable_mode": numeric_stable_mode, "axis": axis},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    diff = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("elementwise_sub", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [diff]}, attrs={"axis": -1})
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square", inputs={"X": [diff]}, outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_variable_for_type_inference(x.dtype)
    loss = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Diff": [diff], "Out": [loss]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    residual = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("huber_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Residual": [residual], "Out": [out]},
                     attrs={"delta": delta})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_loss", inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [loss]}, attrs={"epsilon": epsilon})
    return loss


def mse_loss(input, label):
    return mean(square_error_cost(input, label))


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kldiv_loss", inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [loss]}, attrs={"reduction": reduction})
    return loss


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op("label_smooth", inputs=inputs, outputs={"Out": [out]},
                     attrs={"epsilon": float(epsilon)})
    return out


# -- shape manipulation ----------------------------------------------------
def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op("reshape2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out, act)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op("squeeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op("unsqueeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op("flatten2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": axis})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op("transpose2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": list(perm)})
    return out


def stack(x, axis=0, name=None):
    helper = LayerHelper("stack", name=name)
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("stack", inputs={"X": xs}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op("unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    ndim = len(input.shape)
    dim = dim % ndim
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "sections": [], "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_variable_for_type_inference(input.dtype) for _ in range(n)]
    helper.append_op("split", inputs={"X": [input]}, outputs={"Out": outs}, attrs=attrs)
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather_nd", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter",
                     inputs={"X": [input], "Ids": [index], "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends), "decrease_axis": []})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value), "data_format": data_format})
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(VarType.FP32)
    helper.append_op("one_hot", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"depth": depth})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": int(k)})
    values.stop_gradient = True
    indices.stop_gradient = True
    return values, indices


def argmax(x, axis=0, name=None):
    helper = LayerHelper("arg_max", name=name)
    out = helper.create_variable_for_type_inference(VarType.INT64, stop_gradient=True)
    helper.append_op("arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=0, name=None):
    helper = LayerHelper("arg_min", name=name)
    out = helper.create_variable_for_type_inference(VarType.INT64, stop_gradient=True)
    helper.append_op("arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    ids = helper.create_variable_for_type_inference(VarType.INT64, stop_gradient=True)
    helper.append_op("argsort", inputs={"X": [input]},
                     outputs={"Out": [out], "Indices": [ids]},
                     attrs={"axis": axis, "descending": descending})
    return out, ids


def where(condition, x, y=None, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("where", inputs={"Condition": [condition], "X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def cond_value(cond, x, y):
    return where(cond, x, y)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1, data_format="NCHW"):
    helper = LayerHelper("bilinear_interp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op("bilinear_interp", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True, data_format="NCHW"):
    helper = LayerHelper("nearest_interp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op("nearest_interp", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(VarType.INT32, stop_gradient=True)
    helper.append_op("shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": int(x.dtype), "out_dtype": int(dtype)})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    if (isinstance(input, Variable)
            and input.type == VarType.LOD_TENSOR_ARRAY):
        # concat over a LoDTensorArray (reference concat accepts one):
        # lower through tensor_array_to_tensor
        from .control_flow import tensor_array_to_tensor

        return tensor_array_to_tensor(input, axis=axis, name=name)[0]
    xs = input if isinstance(input, (list, tuple)) else [input]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("concat", inputs={"X": xs}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """reference: layers/metric_op.py accuracy (top_k + accuracy ops)."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference(VarType.FP32)
    correct = correct or helper.create_variable_for_type_inference(VarType.INT32)
    total = total or helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op(
        "accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    acc_out.stop_gradient = True
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=2 ** 12 - 1, topk=1,
        slide_steps=1):
    """Running ROC-AUC (reference: layers/metric_op.py auc — two auc ops
    over persistable bin-count states: a sliding-window batch AUC and a
    global AUC; ops/parity_ops.py implements auc_op.h's stat layout)."""
    from .tensor import create_global_var

    helper = LayerHelper("auc")

    def _stats(tag, s):
        n = (1 + s) * (num_thresholds + 1) + (1 if s > 0 else 0)
        pos = create_global_var([n], 0.0, "int64", persistable=True,
                                name=helper.name + f"_{tag}_pos")
        neg = create_global_var([n], 0.0, "int64", persistable=True,
                                name=helper.name + f"_{tag}_neg")
        return pos, neg

    batch_pos, batch_neg = _stats("batch", slide_steps)
    stat_pos, stat_neg = _stats("global", 0)

    def _auc_op(pos, neg, s):
        out = helper.create_variable_for_type_inference(VarType.FP64)
        helper.append_op(
            "auc",
            inputs={"Predict": [input], "Label": [label],
                    "StatPos": [pos], "StatNeg": [neg]},
            outputs={"AUC": [out], "StatPosOut": [pos],
                     "StatNegOut": [neg]},
            attrs={"curve": curve, "num_thresholds": num_thresholds,
                   "slide_steps": s})
        return out

    batch_auc_out = _auc_op(batch_pos, batch_neg, slide_steps)
    auc_out = _auc_op(stat_pos, stat_neg, 0)
    return auc_out, batch_auc_out, [batch_pos, batch_neg,
                                    stat_pos, stat_neg]


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"step": float(value)})
    return out


def cos_sim(X, Y):
    xn = l2_normalize(X, axis=-1)
    yn = l2_normalize(Y, axis=-1)
    helper = LayerHelper("cos_sim")
    prod = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("elementwise_mul", inputs={"X": [xn], "Y": [yn]},
                     outputs={"Out": [prod]}, attrs={"axis": -1})
    return reduce_sum(prod, dim=-1, keep_dim=True)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "min": float(min),
                            "max": float(max), "seed": seed, "dtype": int(dtype)})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": float(mean),
                            "std": float(std), "seed": seed, "dtype": int(dtype)})
    return out


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None, name=None):
    """Register a user Python callable as an op inside the static
    program (reference: layers/nn.py py_func ->
    operators/py_func_op.cc).  ``func(*x_arrays) -> out_arrays`` runs
    host-side at execution time via ``jax.pure_callback`` — the rest of
    the program stays one XLA computation.  ``out`` must be pre-created
    with correct shape/dtype (the reference's contract too); pass
    ``out=None`` for side-effect-only debug calls.  ``backward_func``
    receives (x, out, out_grad) minus ``skip_vars_in_backward_input``
    and returns the gradient arrays for ``x``."""
    from ..ops import py_func_op as _pf

    helper = LayerHelper("py_func", name=name)
    xs = [x] if isinstance(x, Variable) else list(x or [])
    single = isinstance(out, Variable)
    outs = [out] if single else list(out or [])
    skip = (skip_vars_in_backward_input if skip_vars_in_backward_input
            is not None else [])
    if isinstance(skip, Variable):
        skip = [skip]
    attrs = {"forward_callable_id": _pf.register_callable(func),
             "backward_callable_id":
                 (_pf.register_callable(backward_func)
                  if backward_func is not None else -1),
             "backward_skip_vars": [v.name for v in skip]}
    helper.append_op("py_func", inputs={"X": xs},
                     outputs={"Out": outs}, attrs=attrs)
    if not outs:
        return None
    return outs[0] if single else outs


def fused_multihead_attention(q, k, v, bias_qk=None, scale=0.0, causal=False,
                              dropout_rate=0.0, name=None):
    """Fused scaled-dot-product attention over (b, heads, seq, head_dim)
    tensors; lowers to the Pallas flash-attention kernel on TPU
    (reference: operators/fused/multihead_matmul_op.cu).  With
    dropout_rate > 0 the attention-probs dropout runs INSIDE the kernel
    from a per-step seed saved as the Seed output (the backward
    regenerates the masks from it — nothing mask-shaped is stored)."""
    helper = LayerHelper("fused_multihead_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias_qk is not None:
        inputs["BiasQK"] = [bias_qk]
    outputs = {"Out": [out]}
    if dropout_rate > 0.0:
        outputs["Seed"] = [
            helper.create_variable_for_type_inference("float32")]
    # lse residual (f32): saved so the grad op can run the flash backward
    # kernel without replaying the forward; a (1,)-sentinel on fallback
    outputs["Lse"] = [helper.create_variable_for_type_inference("float32")]
    helper.append_op("fused_multihead_attention", inputs=inputs,
                     outputs=outputs,
                     attrs={"scale": float(scale), "causal": bool(causal),
                            "dropout_rate": float(dropout_rate)})
    return out


# public surface for the star-import in layers/__init__.py (keeps np/
# LayerHelper/Variable/initializers out of the fluid.layers namespace)
__all__ = [
    _n for _n, _v in list(globals().items())
    if not _n.startswith("_") and callable(_v)
    and getattr(_v, "__module__", None) == __name__
]
