"""fluid.layers namespace (reference: python/paddle/fluid/layers/)."""
from __future__ import annotations

from ..framework.core import (
    Variable,
    default_main_program,
    in_dygraph_mode,
)
from ..framework.dtype import VarType, convert_dtype
from . import nn
from . import tensor
from .nn import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    assign,
    create_global_var,
    create_parameter,
    create_tensor,
    diag,
    eye,
    fill_constant,
    fill_constant_batch_size_like,
    linspace,
    ones,
    ones_like,
    reverse,
    sums,
    zeros,
    zeros_like,
)
from .tensor import range as range_  # 'range' shadows builtin; both exported
range = range_  # fluid.layers.range (reference exports it despite the builtin)
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()

from . import control_flow
from .control_flow import (  # noqa: F401
    IfElse,
    Switch,
    While,
    array_length,
    array_pop,
    array_read,
    array_write,
    case,
    cond,
    create_array,
    equal,
    less_than,
    switch_case,
    tensor_array_to_tensor,
    while_loop,
)
from . import sequence_lod
from .sequence_lod import (  # noqa: F401
    lod_append,
    lod_reset,
    reorder_lod_tensor_by_rank,
    sequence_scatter,
    im2sequence,
    row_conv,
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_erase,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_mask,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
)
from . import rnn as rnn_module
from .rnn import (  # noqa: F401
    GRUCell,
    LSTMCell,
    RNNCell,
    StaticRNN,
    beam_search,
    beam_search_decode,
    birnn,
    dynamic_gru,
    dynamic_lstm,
    gru,
    lstm,
)
from .rnn import rnn  # noqa: F401  (function wins, as in the reference)
from . import decoder as decoder_module
from .decoder import (  # noqa: F401
    BasicDecoder,
    BeamSearchDecoder,
    DecodeHelper,
    Decoder,
    DynamicRNN,
    GreedyEmbeddingHelper,
    SampleEmbeddingHelper,
    TrainingHelper,
    dynamic_decode,
)
from . import detection
from .detection import (  # noqa: F401
    box_decoder_and_assign,
    collect_fpn_proposals,
    distribute_fpn_proposals,
    generate_mask_labels,
    generate_proposal_labels,
    generate_proposals,
    locality_aware_nms,
    multi_box_head,
    prroi_pool,
    psroi_pool,
    retinanet_detection_output,
    retinanet_target_assign,
    roi_perspective_transform,
    rpn_target_assign,
    polygon_box_transform,
    anchor_generator,
    bipartite_match,
    box_clip,
    box_coder,
    density_prior_box,
    detection_output,
    iou_similarity,
    multiclass_nms,
    prior_box,
    roi_align,
    roi_pool,
    ssd_loss,
    target_assign,
    yolo_box,
    yolov3_loss,
)
from . import nn_tail
from .nn_tail import *  # noqa: F401,F403  (layers long tail)
from ..distribution import (  # noqa: F401  (reference: layers/distributions.py)
    Categorical,
    MultivariateNormalDiag,
    Normal,
    Uniform,
)
from . import learning_rate_scheduler
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay,
    exponential_decay,
    natural_exp_decay,
    inverse_time_decay,
    polynomial_decay,
    piecewise_decay,
    cosine_decay,
    linear_lr_warmup,
)


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         type=VarType.LOD_TENSOR, stop_gradient=True):
    """reference: python/paddle/fluid/data_feeder / layers/io.py data.

    With append_batch_size=True (fluid.layers.data behavior) a leading -1
    batch dim is prepended; fluid.data passes shape verbatim.
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().global_block()
    if block.has_var(name):
        return block.var(name)
    return block.create_var(
        name=name,
        shape=shape,
        dtype=convert_dtype(dtype),
        lod_level=lod_level,
        is_data=True,
        stop_gradient=stop_gradient,
        need_check_feed=True,
    )
