"""Control-flow layers: cond / while_loop / While / case / switch_case.

Reference: python/paddle/fluid/layers/control_flow.py (While:1024,
cond:2150, case, switch_case, increment, less_than...).  The sub-blocks
are real Blocks in the Program (serializable, transpiler-visible); the
ops lower to lax.cond/lax.while_loop (ops/control_ops.py).

Known scope cut (documented): LoDTensorArray-based dynamic RNN
(array_write/array_read + While) needs dynamic-length arrays that XLA
cannot express; use while_loop with fixed-shape carries or lax.scan-style
rnn layers instead.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

from ..framework.core import Variable, default_main_program
from ..framework.dtype import VarType
from ..layer_helper import LayerHelper
from . import nn as nn_layers
from . import tensor as tensor_layers


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _free_vars(blocks, parent):
    """Outer vars read by `blocks` (incl. nested sub-blocks): the explicit
    Input list for control-flow ops, so executor read-set analysis and
    grad replay see through the block boundary."""
    from ..framework.core import Block as _Block

    free = []
    seen = set()

    def visit(blk, produced):
        produced = set(produced)
        for op_ in blk.ops:
            for n in op_.input_arg_names:
                if n in produced or n in seen or n == "@EMPTY@":
                    continue
                if parent._find_var_recursive(n) is not None and not blk.has_var(n):
                    seen.add(n)
                    free.append(n)
            for k, v in op_.attrs.items():
                if isinstance(v, _Block):
                    visit(v, produced)
                elif isinstance(v, int) and k.endswith("_block"):
                    visit(parent.program.blocks[v], produced)
            produced.update(op_.output_arg_names)

    for blk in blocks:
        visit(blk, set())
    return free


def cond(pred: Variable, true_fn: Callable = None, false_fn: Callable = None,
         name=None):
    """reference: control_flow.py:2150."""
    helper = LayerHelper("cond", name=name)
    prog = default_main_program()
    parent = prog.current_block()

    tb = prog._create_block()
    t_out = _to_list(true_fn() if true_fn is not None else None)
    prog._rollback()
    fb = prog._create_block()
    f_out = _to_list(false_fn() if false_fn is not None else None)
    prog._rollback()

    if len(t_out) != len(f_out):
        raise ValueError(
            f"true_fn returns {len(t_out)} outputs, false_fn {len(f_out)} — "
            f"branches must match")
    outs = []
    for tv in t_out:
        outs.append(parent.create_var(
            name=helper.name + f"_out_{len(outs)}",
            shape=tv.shape, dtype=tv.dtype))
    free = _free_vars([tb, fb], parent)
    parent.append_op(
        "cond",
        inputs={"Cond": [pred], "Input": free},
        outputs={"Out": outs},
        attrs={
            "true_block": tb,
            "false_block": fb,
            "true_out_names": [v.name for v in t_out],
            "false_out_names": [v.name for v in f_out],
            "input_names": free,
        },
    )
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test=False, name=None):
    """reference: control_flow.py while_loop (2.0 API)."""
    helper = LayerHelper("while_loop", name=name)
    prog = default_main_program()
    parent = prog.current_block()
    loop_vars = _to_list(loop_vars)

    cb = prog._create_block()
    c = cond_fn(*loop_vars)
    prog._rollback()
    bb = prog._create_block()
    body_out = _to_list(body_fn(*loop_vars))
    prog._rollback()
    if len(body_out) != len(loop_vars):
        raise ValueError("body must return as many values as loop_vars")

    outs = [parent.create_var(name=helper.name + f"_out_{i}",
                              shape=v.shape, dtype=v.dtype)
            for i, v in enumerate(loop_vars)]
    carry_names = [v.name for v in loop_vars]
    free = [n for n in _free_vars([cb, bb], parent) if n not in carry_names]
    parent.append_op(
        "while_loop",
        inputs={"X": loop_vars, "Input": free},
        outputs={"Out": outs},
        attrs={
            "cond_block": cb,
            "body_block": bb,
            "carry_names": carry_names,
            "cond_out_name": c.name,
            "body_out_names": [v.name for v in body_out],
            "input_names": free,
        },
    )
    return outs[0] if len(outs) == 1 else outs


class While:
    """Old-style While block (reference: control_flow.py:1024).

    with While(cond_var).block(): ... ops ...; the block must reassign
    cond_var.  Vars written inside that pre-exist outside are carried."""

    def __init__(self, cond: Variable, is_test=False, name=None):
        self._cond = cond
        self.helper = LayerHelper("while", name=name)

    def block(self):
        import contextlib

        prog = default_main_program()
        parent = prog.current_block()
        outer_vars = set()
        blk = prog.blocks
        b = parent
        while b is not None:
            outer_vars |= set(b.vars.keys())
            b = b.parent_block

        @contextlib.contextmanager
        def _ctx():
            sub = prog._create_block()
            yield
            prog._rollback()
            written = set()
            for op_ in sub.ops:
                written.update(op_.output_arg_names)
            carry = sorted((written & outer_vars) - {self._cond.name})
            free = [n for n in _free_vars([sub], parent)
                    if n not in carry and n != self._cond.name]
            parent.append_op(
                "while",
                inputs={"Cond": [self._cond], "X": carry, "Input": free},
                outputs={"XOut": carry, "CondOut": [self._cond]},
                attrs={
                    "sub_block": sub,
                    "cond_name": self._cond.name,
                    "carry_names": carry,
                    "input_names": free,
                },
            )

        return _ctx()


def case(pred_fn_pairs, default=None, name=None):
    """reference: control_flow.py case — chained conds."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    if default is not None:
        return cond(pred, fn, default)
    return cond(pred, fn, fn)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: control_flow.py switch_case."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = list(enumerate(branch_fns))
    helper = LayerHelper("switch_case", name=name)

    def make_pred(i):
        iv = tensor_layers.fill_constant([1], branch_index.dtype, float(i))
        eq = helper.create_variable_for_type_inference(VarType.BOOL)
        helper.append_op("equal", inputs={"X": [branch_index], "Y": [iv]},
                         outputs={"Out": [eq]}, attrs={"axis": -1})
        return eq

    pred_fn_pairs = [(make_pred(i), fn) for i, fn in pairs]
    return case(pred_fn_pairs, default)


# re-exports used by reference-era scripts
increment = nn_layers.increment


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than")
    out = cond or helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("less_than", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    out = cond or helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out
