"""Control-flow layers: cond / while_loop / While / case / switch_case.

Reference: python/paddle/fluid/layers/control_flow.py (While:1024,
cond:2150, case, switch_case, increment, less_than...).  The sub-blocks
are real Blocks in the Program (serializable, transpiler-visible); the
ops lower to lax.cond/lax.while_loop (ops/control_ops.py).

LoDTensorArray inside While/cond bodies: dynamic-length arrays can't be
fixed-shape lax carries, so an enclosing while/cond whose blocks hold
array ops runs as a HOST loop driving device kernels
(ops/control_ops.py _blocks_contain_host) — the reference While op's
own architecture.  Fixed-shape recurrence should still prefer
while_loop tensor carries or the lax.scan-style rnn layers, which stay
fully compiled.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

from ..framework.core import Variable, default_main_program
from ..framework.dtype import VarType
from ..layer_helper import LayerHelper
from . import nn as nn_layers
from . import tensor as tensor_layers


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _free_vars(blocks, parent):
    """Outer vars read by `blocks` (incl. nested sub-blocks): the explicit
    Input list for control-flow ops, so executor read-set analysis and
    grad replay see through the block boundary."""
    from ..framework.core import Block as _Block

    free = []
    seen = set()

    def visit(blk, produced):
        produced = set(produced)
        for op_ in blk.ops:
            for n in op_.input_arg_names:
                if n in produced or n in seen or n == "@EMPTY@":
                    continue
                if parent._find_var_recursive(n) is not None and not blk.has_var(n):
                    seen.add(n)
                    free.append(n)
            for k, v in op_.attrs.items():
                if isinstance(v, _Block):
                    visit(v, produced)
                elif isinstance(v, int) and k.endswith("_block"):
                    visit(parent.program.blocks[v], produced)
            produced.update(op_.output_arg_names)

    for blk in blocks:
        visit(blk, set())
    return free


# Sentinel value a branch yields for a name the other branch binds with a
# real tensor but this one leaves unset (the dygraph_to_static early-return
# machinery): matches the reference's RETURN_NO_VALUE_MAGIC_NUM
# (dygraph_to_static/return_transformer.py).
RETURN_NO_VALUE_MAGIC = 1.77113e27


def magic_fill_value(dtype):
    """The RETURN_NO_VALUE sentinel, clamped to what the slot's dtype
    can hold: 1.77113e27 overflows integer fills to INT_MIN garbage
    (code-review r5), so integer slots use their dtype max and bool
    slots True."""
    from ..framework.dtype import VarType, to_numpy_dtype
    import numpy as _np

    try:
        np_dt = _np.dtype(to_numpy_dtype(dtype)
                          if isinstance(dtype, (int, VarType)) else dtype)
    except Exception:
        return RETURN_NO_VALUE_MAGIC
    if np_dt.kind in "iu":
        return int(_np.iinfo(np_dt).max)
    if np_dt.kind == "b":
        return True
    return RETURN_NO_VALUE_MAGIC


class CarryInitMismatch(TypeError):
    """while_loop carry i entered as a python value but the body binds a
    Variable; .slots is [(i, body_out_var)].  The first (abandoned)
    trace's sub-blocks stay in the program as unreferenced dead blocks —
    only blocks reachable through op attrs execute."""

    def __init__(self, slots):
        super().__init__(
            f"while_loop carries {[i for i, _ in slots]} are python "
            "values but their body outputs are Variables; seed them "
            "with same-shaped tensors")
        self.slots = slots


def _align_branch_outputs(prog, tb, fb, t_out, f_out):
    """Positions where exactly one branch returned a Variable and the
    other a python scalar/None/UNDEFINED (a name the branch left
    unbound — dygraph_to_static's UndefinedVar analog) get a constant
    of the SAME shape/dtype appended inside the deficient branch block,
    so the cond op's per-position contract holds (None/UNDEFINED become
    the reference's RETURN_NO_VALUE magic number)."""
    def is_undef(v):
        return v is None or type(v).__name__ == "_Undefined"

    def fix(blk, vals, others):
        out = list(vals)
        need = [i for i, (v, o) in enumerate(zip(vals, others))
                if not isinstance(v, Variable) and isinstance(o, Variable)]
        if not need:
            return out
        saved = prog.current_block_idx
        prog.current_block_idx = blk.idx
        try:
            for i in need:
                o = others[i]
                v = out[i]
                if is_undef(v):
                    fill = magic_fill_value(o.dtype)
                elif isinstance(v, bool):
                    fill = bool(v)
                elif isinstance(v, (int, float)):
                    fill = float(v)
                else:
                    raise TypeError(
                        f"cond branch output {i} is {type(v).__name__}, "
                        "the other branch a tensor — branches must bind "
                        "compatible values")
                out[i] = tensor_layers.fill_constant(
                    list(o.shape), o.dtype, fill)
        finally:
            prog.current_block_idx = saved
        return out

    t_out, f_out = fix(tb, t_out, f_out), fix(fb, f_out, t_out)
    for i, (tv, fv) in enumerate(zip(t_out, f_out)):
        if not isinstance(tv, Variable) and not isinstance(fv, Variable) \
                and (is_undef(tv) or is_undef(fv)):
            raise ValueError(
                f"cond output {i}: a name assigned in neither branch (or "
                "only as a python value in one) escapes a tensor-condition "
                "`if` — bind it before the if or in both branches")
    return t_out, f_out


def cond(pred: Variable, true_fn: Callable = None, false_fn: Callable = None,
         name=None):
    """reference: control_flow.py:2150."""
    helper = LayerHelper("cond", name=name)
    prog = default_main_program()
    parent = prog.current_block()

    tb = prog._create_block()
    t_out = _to_list(true_fn() if true_fn is not None else None)
    prog._rollback()
    fb = prog._create_block()
    f_out = _to_list(false_fn() if false_fn is not None else None)
    prog._rollback()

    if len(t_out) != len(f_out):
        raise ValueError(
            f"true_fn returns {len(t_out)} outputs, false_fn {len(f_out)} — "
            f"branches must match")
    t_out, f_out = _align_branch_outputs(prog, tb, fb, t_out, f_out)
    # opaque python objects (dicts, sets...) a branch mutated but did
    # not rebind come back as the SAME object from both branches: pass
    # them through by identity instead of forcing a tensor slot (their
    # host-side mutation already happened while tracing — plain-python
    # semantics, matching the d2s dispatch fallback)
    merged: List = [None] * len(t_out)
    var_idx: List[int] = []
    def _equal_plain_values(a, b):
        """Equal non-Variable values bound separately in each branch
        (e.g. `x = 0.5` in both bodies) are distinct objects — identity
        fails but the merge is still unambiguous.  Guarded: types whose
        __eq__ is elementwise or raising (numpy arrays...) count as not
        equal and fall through to the error below."""
        if type(a) is not type(b):
            return False
        try:
            return bool(a == b)
        except Exception:
            return False

    for i, (tv, fv) in enumerate(zip(t_out, f_out)):
        if (not isinstance(tv, Variable) and not isinstance(fv, Variable)
                and (tv is fv or _equal_plain_values(tv, fv))):
            merged[i] = tv
        elif isinstance(tv, Variable) and isinstance(fv, Variable):
            var_idx.append(i)
        elif type(tv) is type(fv) and not isinstance(tv, Variable):
            raise ValueError(
                f"cond output {i}: branches return unequal python "
                f"{type(tv).__name__} values ({tv!r} vs {fv!r}) — a "
                "value that differs by branch must be a tensor; bind it "
                "with fill_constant (or return the same value)")
        else:
            raise ValueError(
                f"cond output {i}: branches return incompatible kinds "
                f"({type(tv).__name__} vs {type(fv).__name__}) — bind a "
                "tensor in both branches or the same python object")
    t_out = [t_out[i] for i in var_idx]
    f_out = [f_out[i] for i in var_idx]
    outs = []
    for tv in t_out:
        ov = parent.create_var(
            name=helper.name + f"_out_{len(outs)}",
            shape=tv.shape, dtype=tv.dtype)
        ov.type = tv.type  # TensorArray outputs stay array-typed
        outs.append(ov)
    free = _free_vars([tb, fb], parent)
    # a branch may RETURN an outer var it never touched (a capture
    # default for a name only the other branch assigns): such names
    # appear only in the out-name attrs, so the op-input scan above
    # can't see them — add them to Input so the runtime env has them
    for v in list(t_out) + list(f_out):
        if (isinstance(v, Variable) and v.name not in free
                and not tb.has_var(v.name) and not fb.has_var(v.name)
                and parent._find_var_recursive(v.name) is not None):
            free.append(v.name)
    parent.append_op(
        "cond",
        inputs={"Cond": [pred], "Input": free},
        outputs={"Out": outs},
        attrs={
            "true_block": tb,
            "false_block": fb,
            "true_out_names": [v.name for v in t_out],
            "false_out_names": [v.name for v in f_out],
            "input_names": free,
        },
    )
    for ov, i in zip(outs, var_idx):
        merged[i] = ov
    if not merged:
        return None
    return merged[0] if len(merged) == 1 else merged


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test=False, name=None):
    """reference: control_flow.py while_loop (2.0 API)."""
    helper = LayerHelper("while_loop", name=name)
    prog = default_main_program()
    parent = prog.current_block()
    loop_vars = _to_list(loop_vars)

    cb = prog._create_block()
    c = cond_fn(*loop_vars)
    prog._rollback()
    bb = prog._create_block()
    body_out = _to_list(body_fn(*loop_vars))
    prog._rollback()
    if len(body_out) != len(loop_vars):
        raise ValueError("body must return as many values as loop_vars")
    mism = [(i, bo) for i, (lv, bo) in enumerate(zip(loop_vars, body_out))
            if not isinstance(lv, Variable) and isinstance(bo, Variable)]
    if mism:
        # a carry entered as python None/scalar but the body binds a
        # tensor (dygraph_to_static early-return slots): the caller can
        # catch this, seed the carry with a same-shaped constant and
        # retry (convert_operators.convert_while_loop does)
        raise CarryInitMismatch(mism)

    outs = []
    for i, v in enumerate(loop_vars):
        ov = parent.create_var(name=helper.name + f"_out_{i}",
                               shape=v.shape, dtype=v.dtype)
        ov.type = v.type  # TensorArray carries stay array-typed
        outs.append(ov)
    carry_names = [v.name for v in loop_vars]
    free = [n for n in _free_vars([cb, bb], parent) if n not in carry_names]
    parent.append_op(
        "while_loop",
        inputs={"X": loop_vars, "Input": free},
        outputs={"Out": outs},
        attrs={
            "cond_block": cb,
            "body_block": bb,
            "carry_names": carry_names,
            "cond_out_name": c.name,
            "body_out_names": [v.name for v in body_out],
            "input_names": free,
        },
    )
    return outs[0] if len(outs) == 1 else outs


class While:
    """Old-style While block (reference: control_flow.py:1024).

    with While(cond_var).block(): ... ops ...; the block must reassign
    cond_var.  Vars written inside that pre-exist outside are carried."""

    def __init__(self, cond: Variable, is_test=False, name=None):
        self._cond = cond
        self.helper = LayerHelper("while", name=name)

    def block(self):
        import contextlib

        prog = default_main_program()
        parent = prog.current_block()
        outer_vars = set()
        blk = prog.blocks
        b = parent
        while b is not None:
            outer_vars |= set(b.vars.keys())
            b = b.parent_block

        @contextlib.contextmanager
        def _ctx():
            sub = prog._create_block()
            yield
            prog._rollback()
            written = set()
            for op_ in sub.ops:
                written.update(op_.output_arg_names)
            carry = sorted((written & outer_vars) - {self._cond.name})
            free = [n for n in _free_vars([sub], parent)
                    if n not in carry and n != self._cond.name]
            parent.append_op(
                "while",
                inputs={"Cond": [self._cond], "X": carry, "Input": free},
                outputs={"XOut": carry, "CondOut": [self._cond]},
                attrs={
                    "sub_block": sub,
                    "cond_name": self._cond.name,
                    "carry_names": carry,
                    "input_names": free,
                },
            )

        return _ctx()


def case(pred_fn_pairs, default=None, name=None):
    """reference: control_flow.py case — chained conds."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    if default is not None:
        return cond(pred, fn, default)
    return cond(pred, fn, fn)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: control_flow.py switch_case."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = list(enumerate(branch_fns))
    helper = LayerHelper("switch_case", name=name)

    def make_pred(i):
        iv = tensor_layers.fill_constant([1], branch_index.dtype, float(i))
        eq = helper.create_variable_for_type_inference(VarType.BOOL)
        helper.append_op("equal", inputs={"X": [branch_index], "Y": [iv]},
                         outputs={"Out": [eq]}, attrs={"axis": -1})
        return eq

    pred_fn_pairs = [(make_pred(i), fn) for i, fn in pairs]
    return case(pred_fn_pairs, default)


# re-exports used by reference-era scripts
increment = nn_layers.increment


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than")
    out = cond or helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("less_than", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    out = cond or helper.create_variable_for_type_inference(VarType.BOOL)
    helper.append_op("equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


# --------------------------------------------------------------------------
# LoDTensorArray layers (reference: control_flow.py array_write:1485,
# array_read:1595, array_length, create_array, tensor.py
# tensor_array_to_tensor).  Host-side arrays; see ops/control_ops.py for
# the scope note on use inside While bodies.
# --------------------------------------------------------------------------
def create_array(dtype, initialized_list=None):
    helper = LayerHelper("create_array")
    out = helper.create_variable_for_type_inference(dtype)
    out.type = VarType.LOD_TENSOR_ARRAY
    helper.append_op("create_array", inputs={}, outputs={"Out": [out]})
    if initialized_list:
        for i, x in enumerate(initialized_list):
            array_write(x, tensor_layers.fill_constant([1], "int64", i), out)
    return out


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op("write_to_array",
                     inputs={"X": [x], "I": [i], "Array": [array]},
                     outputs={"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("read_from_array", inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(VarType.INT64)
    helper.append_op("lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


def array_pop(array, index=-1):
    """Pop element ``index`` (static python int) off a LoDTensorArray,
    mutating it in place; used by dygraph_to_static list conversion
    (reference: dygraph_to_static/list_transformer.py convert_list_pop)."""
    helper = LayerHelper("array_pop")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("tensor_array_pop", inputs={"X": [array]},
                     outputs={"Out": [out]}, attrs={"index": int(index)})
    return out


def tensor_array_to_tensor(input, axis=0, name=None, use_stack=False):
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference(VarType.INT32)
    helper.append_op("tensor_array_to_tensor", inputs={"X": [input]},
                     outputs={"Out": [out], "OutIndex": [idx]},
                     attrs={"axis": axis, "use_stack": use_stack})
    return out, idx


# --------------------------------------------------------------------------
# IfElse / Switch (reference: control_flow.py IfElse:3086, Switch:3375)
# --------------------------------------------------------------------------
class IfElse:
    """Row-partitioned conditional (reference semantics: split rows by a
    bool condition, run each branch on its partition, merge).

    TPU-native realization: both branches run on the FULL batch and the
    merge selects rows by the condition — identical results for the
    row-wise computations IfElse supports, with static shapes for XLA
    (the reference's gather/scatter by condition index has data-dependent
    shapes)."""

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.cond = cond  # (N, 1) bool
        self._in_true = None
        self._true_out = None
        self._false_out = None
        self._inputs = []

    class _Branch:
        def __init__(self, owner, is_true):
            self.owner = owner
            self.is_true = is_true

        def __enter__(self):
            self.owner._in_true = self.is_true
            return self

        def __exit__(self, *a):
            self.owner._in_true = None
            return False

    def true_block(self):
        return IfElse._Branch(self, True)

    def false_block(self):
        return IfElse._Branch(self, False)

    def input(self, x):
        """Inside a branch: the branch's view of x (full batch here; the
        merge applies the row condition)."""
        if self._in_true is None:
            raise RuntimeError("IfElse.input() must be called inside "
                               "true_block()/false_block()")
        return x

    def output(self, *outs):
        if self._in_true is True:
            self._true_out = list(outs)
        elif self._in_true is False:
            self._false_out = list(outs)
        else:
            raise RuntimeError("IfElse.output() must be called inside "
                               "true_block()/false_block()")

    def __call__(self):
        if self._true_out is None or self._false_out is None:
            raise RuntimeError("both branches must set output()")
        if len(self._true_out) != len(self._false_out):
            raise ValueError("branch outputs must pair up")
        helper = LayerHelper("ifelse_merge")
        merged = []
        for t, f in zip(self._true_out, self._false_out):
            out = helper.create_variable_for_type_inference(t.dtype)
            helper.append_op("where",
                             inputs={"Condition": [self.cond], "X": [t],
                                     "Y": [f]},
                             outputs={"Out": [out]})
            merged.append(out)
        return merged


class Switch:
    """Scoped case builder (reference: control_flow.py Switch:3375),
    used mainly by LR schedulers:

        with fluid.layers.Switch() as switch:
            with switch.case(cond1):  assign(a, out)
            with switch.default():    assign(b, out)

    First matching case wins.  TPU-native lowering: each case body is
    captured, its writes are redirected to per-case temporaries, and the
    final value of every written var is a where-chain over the case
    conditions (compute-all + select — static shapes; the bodies are
    tiny scalar LR math in practice)."""

    def __init__(self, name=None):
        self._cases = []       # (cond_var or None, captured ops)
        self._start = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            return False
        self._materialize()
        return False

    class _Case:
        def __init__(self, owner, cond):
            self.owner = owner
            self.cond = cond

        def __enter__(self):
            blk = default_main_program().current_block()
            self.owner._start = len(blk.ops)
            return self

        def __exit__(self, exc_type, *a):
            if exc_type is not None:
                return False
            blk = default_main_program().current_block()
            captured = blk.ops[self.owner._start:]
            del blk.ops[self.owner._start:]
            self.owner._cases.append((self.cond, captured))
            self.owner._start = None
            return False

    def case(self, condition):
        return Switch._Case(self, condition)

    def default(self):
        return Switch._Case(self, None)

    def _materialize(self):
        from ..framework import unique_name

        blk = default_main_program().current_block()
        # re-emit each case with writes renamed to temporaries
        case_vals = []  # (cond, {orig_name: temp_name})
        for ci, (cond, ops) in enumerate(self._cases):
            rename = {}
            for op_ in ops:
                new_inputs = {s_: [rename.get(n, n) for n in ns]
                              for s_, ns in op_.inputs.items()}
                new_outputs = {}
                for s_, ns in op_.outputs.items():
                    outs = []
                    for n in ns:
                        if n == "@EMPTY@":
                            outs.append(n)
                            continue
                        tmp = rename.get(n)
                        if tmp is None:
                            tmp = unique_name.generate(f"{n}@SWITCH{ci}")
                            v = blk._find_var_recursive(n)
                            blk.create_var(
                                name=tmp,
                                dtype=v.dtype if v is not None else "float32")
                            rename[n] = tmp
                        outs.append(tmp)
                    new_outputs[s_] = outs
                blk.append_op(op_.type, inputs=new_inputs,
                              outputs=new_outputs, attrs=dict(op_.attrs))
            case_vals.append((cond, rename))

        # merge per written var: first matching case wins, fallback = the
        # var's pre-switch value
        written = []
        for _, rename in case_vals:
            for n in rename:
                if n not in written:
                    written.append(n)
        helper = LayerHelper("switch_merge")
        for name in written:
            current = name  # pre-switch value as the final fallback
            for cond, rename in reversed(case_vals):
                if name not in rename:
                    continue
                if cond is None:
                    current = rename[name]
                    continue
                out = unique_name.generate(f"{name}@SWITCH_SEL")
                v = blk._find_var_recursive(name)
                blk.create_var(name=out,
                               dtype=v.dtype if v is not None else "float32")
                blk.append_op("where",
                              inputs={"Condition": [cond],
                                      "X": [rename[name]], "Y": [current]},
                              outputs={"Out": [out]})
                current = out
            blk.append_op("assign", inputs={"X": [current]},
                          outputs={"Out": [name]})

